# TPU trainer image: jax[tpu] via PjRT — ZERO CUDA/NCCL deps (the north
# star's hard requirement; the reference image was tensorflow:latest-gpu,
# tf-trainer-worker.yaml:31).
FROM python:3.12-slim
WORKDIR /app
RUN pip install --no-cache-dir "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir flax optax orbax-checkpoint einops numpy pillow \
       tensorflow-cpu  # tf.data for the TFRecord bridge only; no GPU runtime
COPY pyspark_tf_gke_tpu /app/pyspark_tf_gke_tpu
ENV PYTHONPATH=/app
ENTRYPOINT ["python", "-m", "pyspark_tf_gke_tpu.train.cli"]
