#!/usr/bin/env bash
# Bastion bootstrap (reference start-up.sh:1-89): installs tooling and
# generates the operator helper scripts. Differences: no PySpark/JDK on the
# bastion by default (the Spark driver runs as an in-cluster pod); adds the
# TPU job launcher.
set -euo pipefail

apt-get update
apt-get install -y kubectl google-cloud-cli google-cloud-cli-gke-gcloud-auth-plugin \
    python3.11 python3-pip git

gcloud container clusters get-credentials "${cluster_name}" \
    --zone "${zone}" --project "${project_id}"

# Helper: upload a dataset to the versioned bucket.
cat > /usr/local/bin/upload_dataset.sh <<'SCRIPT'
#!/usr/bin/env bash
set -euo pipefail
FILE="$1"
gsutil cp "$FILE" "gs://${bucket}/$(basename "$FILE")"
echo "uploaded to gs://${bucket}/$(basename "$FILE")"
SCRIPT
chmod +x /usr/local/bin/upload_dataset.sh

# Helper: project-id substitution + ConfigMap apply + workload restart —
# the reference's generated config.sh (start-up.sh:57-88).
cat > /usr/local/bin/apply_config.sh <<'SCRIPT'
#!/usr/bin/env bash
set -euo pipefail
MANIFEST_DIR="$${1:-/opt/tpu-pipeline/infra/k8s}"
for f in "$MANIFEST_DIR"/**/*.yaml; do
  sed "s/\$${PROJECT_ID}/${project_id}/g" "$f" | kubectl apply -f -
done
kubectl rollout restart deployment/spark-master deployment/spark-worker || true
SCRIPT
chmod +x /usr/local/bin/apply_config.sh

echo "bastion ready: upload_dataset.sh, apply_config.sh, kubectl configured"
