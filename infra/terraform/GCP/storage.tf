# Versioned datasets bucket (reference storage.tf:2-14): raw CSVs, the
# Spark-written TFRecord shards, and TPU checkpoint output all live here.

resource "google_storage_bucket" "datasets" {
  name          = "${var.project_id}-${var.datasets_bucket_suffix}"
  location      = var.region
  force_destroy = true

  versioning {
    enabled = true
  }

  uniform_bucket_level_access = true
}
