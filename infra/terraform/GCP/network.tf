# VPC + subnet with secondary ranges for pods/services, NAT for the private
# cluster, and the firewall pair the reference uses (internal-allow +
# master->node webhook ports) — reference network.tf:2-67. Unchanged in
# spirit; TPU pods speak over the same pod network (ICI traffic never
# leaves the TPU slice and needs no VPC config).

resource "google_compute_network" "vpc" {
  name                    = "${var.cluster_name}-vpc"
  auto_create_subnetworks = false
}

resource "google_compute_subnetwork" "subnet" {
  name          = "${var.cluster_name}-subnet"
  region        = var.region
  network       = google_compute_network.vpc.id
  ip_cidr_range = "10.10.0.0/16"

  secondary_ip_range {
    range_name    = "pods"
    ip_cidr_range = "10.20.0.0/14"
  }
  secondary_ip_range {
    range_name    = "services"
    ip_cidr_range = "10.24.0.0/20"
  }
}

resource "google_compute_router" "router" {
  name    = "${var.cluster_name}-router"
  region  = var.region
  network = google_compute_network.vpc.id
}

resource "google_compute_router_nat" "nat" {
  name                               = "${var.cluster_name}-nat"
  router                             = google_compute_router.router.name
  region                             = var.region
  nat_ip_allocate_option             = "AUTO_ONLY"
  source_subnetwork_ip_ranges_to_nat = "ALL_SUBNETWORKS_ALL_IP_RANGES"
}

resource "google_compute_firewall" "internal_allow" {
  name    = "${var.cluster_name}-internal-allow"
  network = google_compute_network.vpc.name

  allow {
    protocol = "tcp"
  }
  allow {
    protocol = "udp"
  }
  allow {
    protocol = "icmp"
  }
  source_ranges = ["10.10.0.0/16", "10.20.0.0/14", "10.24.0.0/20"]
}

# Control plane -> nodes: admission webhooks + the jax.distributed
# coordinator port so kubectl exec / debugging from the master works.
resource "google_compute_firewall" "master_to_nodes" {
  name    = "${var.cluster_name}-master-to-nodes"
  network = google_compute_network.vpc.name

  allow {
    protocol = "tcp"
    ports    = ["443", "8443", "9443", "8476"]
  }
  source_ranges = ["172.16.0.0/28"]
}
