# Service accounts + Workload Identity. Reference: main.tf:62-95 and the
# KSA annotation in infra/cloud/gcp_spark/spark-k8s-sa.yaml:1-14. The TPU
# workers get their own KSA<->GSA binding for GCS dataset/checkpoint access.

resource "google_service_account" "gke_sa" {
  account_id   = "${var.cluster_name}-gke-sa"
  display_name = "GKE node service account"
}

resource "google_project_iam_member" "gke_sa_logging" {
  project = var.project_id
  role    = "roles/logging.logWriter"
  member  = "serviceAccount:${google_service_account.gke_sa.email}"
}

resource "google_project_iam_member" "gke_sa_monitoring" {
  project = var.project_id
  role    = "roles/monitoring.metricWriter"
  member  = "serviceAccount:${google_service_account.gke_sa.email}"
}

# Spark jobs (KSA spark-sa in default ns) read datasets from the bucket.
resource "google_service_account" "spark_sa" {
  account_id   = "${var.cluster_name}-spark-sa"
  display_name = "Spark workload identity SA"
}

resource "google_service_account_iam_member" "spark_wi_binding" {
  service_account_id = google_service_account.spark_sa.name
  role               = "roles/iam.workloadIdentityUser"
  member             = "serviceAccount:${var.project_id}.svc.id.goog[default/spark-sa]"
}

resource "google_storage_bucket_iam_member" "spark_bucket_viewer" {
  bucket = google_storage_bucket.datasets.name
  role   = "roles/storage.objectViewer"
  member = "serviceAccount:${google_service_account.spark_sa.email}"
}

# TPU workers (KSA tpu-worker-sa) read TFRecord shards and write checkpoints.
resource "google_service_account" "tpu_sa" {
  account_id   = "${var.cluster_name}-tpu-sa"
  display_name = "TPU worker workload identity SA"
}

resource "google_service_account_iam_member" "tpu_wi_binding" {
  service_account_id = google_service_account.tpu_sa.name
  role               = "roles/iam.workloadIdentityUser"
  member             = "serviceAccount:${var.project_id}.svc.id.goog[default/tpu-worker-sa]"
}

resource "google_storage_bucket_iam_member" "tpu_bucket_admin" {
  bucket = google_storage_bucket.datasets.name
  role   = "roles/storage.objectAdmin"
  member = "serviceAccount:${google_service_account.tpu_sa.email}"
}
