# Deployment-time knobs — the analog of the reference's variables.tf
# (infra/cloud/terraform/GCP/variables.tf:1-87), retargeted: the commented-out
# CPU "TF pool" (e2-standard-8, reference main.tf:176-208) becomes a Cloud TPU
# v5e node pool.

variable "project_id" {
  description = "GCP project id"
  type        = string
}

variable "region" {
  description = "Region for the cluster and network"
  type        = string
  default     = "us-central1"
}

variable "zone" {
  description = "Zone for zonal resources (bastion VM, TPU pool)"
  type        = string
  default     = "us-central1-a"
}

variable "cluster_name" {
  description = "GKE cluster name"
  type        = string
  default     = "tpu-pipeline"
}

# --- Spark ETL pool (kept from the reference: 2x e2-standard-4, tainted) ---

variable "spark_machine_type" {
  type    = string
  default = "e2-standard-4"
}

variable "spark_node_count" {
  type    = number
  default = 2
}

# --- TPU training pool (replaces the reference's commented CPU TF pool) ---

variable "tpu_machine_type" {
  description = "TPU VM machine type; ct5lp-hightpu-4t = v5e, 4 chips/VM"
  type        = string
  default     = "ct5lp-hightpu-4t"
}

variable "tpu_topology" {
  description = "TPU slice topology (cloud.google.com/gke-tpu-topology), e.g. 2x2 for v5e-4, 2x4 for v5e-8"
  type        = string
  default     = "2x2"
}

variable "tpu_accelerator" {
  description = "gke-tpu-accelerator node-selector value"
  type        = string
  default     = "tpu-v5-lite-podslice"
}

variable "tpu_node_count" {
  description = "Hosts in the TPU slice (topology chips / chips-per-VM)"
  type        = number
  default     = 1
}

variable "bastion_machine_type" {
  type    = string
  default = "n1-standard-1"
}

variable "datasets_bucket_suffix" {
  description = "Bucket name = <project_id>-<suffix>"
  type        = string
  default     = "datasets"
}
