# GKE cluster + node pools. Reference: infra/cloud/terraform/GCP/main.tf.
# Changes from the reference by design:
#   * the commented-out CPU "TF pool" (2x e2-standard-8, main.tf:176-208)
#     is replaced by a Cloud TPU v5e node pool (ct5lp-hightpu-4t) with
#     placement driven by gke-tpu-accelerator / gke-tpu-topology selectors;
#   * the Spark ETL pool, Workload Identity, autoscaling and private-nodes
#     setup carry over (main.tf:2-143).

terraform {
  required_providers {
    google = {
      source  = "hashicorp/google"
      version = ">= 5.0"
    }
  }
}

provider "google" {
  project = var.project_id
  region  = var.region
}

resource "google_container_cluster" "primary" {
  name     = var.cluster_name
  location = var.zone

  remove_default_node_pool = true
  initial_node_count       = 1
  deletion_protection      = false

  network    = google_compute_network.vpc.id
  subnetwork = google_compute_subnetwork.subnet.id

  ip_allocation_policy {
    cluster_secondary_range_name  = "pods"
    services_secondary_range_name = "services"
  }

  private_cluster_config {
    enable_private_nodes    = true
    enable_private_endpoint = false
    master_ipv4_cidr_block  = "172.16.0.0/28"
  }

  workload_identity_config {
    workload_pool = "${var.project_id}.svc.id.goog"
  }

  cluster_autoscaling {
    enabled = true
    resource_limits {
      resource_type = "cpu"
      minimum       = 1
      maximum       = 10
    }
    resource_limits {
      resource_type = "memory"
      minimum       = 1
      maximum       = 40
    }
  }
}

resource "google_container_node_pool" "default_pool" {
  name     = "default-pool"
  cluster  = google_container_cluster.primary.name
  location = var.zone

  node_count = 1
  node_config {
    machine_type    = "e2-medium"
    service_account = google_service_account.gke_sa.email
    oauth_scopes    = ["https://www.googleapis.com/auth/cloud-platform"]
  }
}

# Spark ETL pool: tainted so only Spark pods land here (the reference's
# workload=spark taint, main.tf:98-143).
resource "google_container_node_pool" "spark_pool" {
  name     = "spark-pool"
  cluster  = google_container_cluster.primary.name
  location = var.zone

  node_count = var.spark_node_count
  autoscaling {
    min_node_count = 1
    max_node_count = var.spark_node_count
  }

  node_config {
    machine_type    = var.spark_machine_type
    service_account = google_service_account.gke_sa.email
    oauth_scopes    = ["https://www.googleapis.com/auth/cloud-platform"]

    labels = { workload = "spark" }
    taint {
      key    = "workload"
      value  = "spark"
      effect = "NO_SCHEDULE"
    }
  }

  management {
    auto_repair  = true
    auto_upgrade = true
  }
}

# TPU training pool. One node per TPU-VM host of the slice; pods select it
# via cloud.google.com/gke-tpu-accelerator + gke-tpu-topology and request
# google.com/tpu chips (see infra/k8s/tpu/). Zero CUDA/NCCL anywhere.
resource "google_container_node_pool" "tpu_pool" {
  name     = "tpu-v5e-pool"
  cluster  = google_container_cluster.primary.name
  location = var.zone

  node_count = var.tpu_node_count

  node_config {
    machine_type    = var.tpu_machine_type
    service_account = google_service_account.gke_sa.email
    oauth_scopes    = ["https://www.googleapis.com/auth/cloud-platform"]

    labels = { workload = "tpu-train" }
    taint {
      key    = "google.com/tpu"
      value  = "present"
      effect = "NO_SCHEDULE"
    }
  }

  placement_policy {
    type         = "COMPACT"
    tpu_topology = var.tpu_topology
  }

  management {
    auto_repair  = true
    auto_upgrade = true
  }
}
