# Operator conveniences (reference outputs.tf:53-80).

output "kubectl_command" {
  value = "gcloud container clusters get-credentials ${google_container_cluster.primary.name} --zone ${var.zone} --project ${var.project_id}"
}

output "ssh_command" {
  value = "gcloud compute ssh ${google_compute_instance.bastion.name} --zone ${var.zone} --project ${var.project_id}"
}

output "datasets_bucket" {
  value = "gs://${google_storage_bucket.datasets.name}"
}

output "tpu_pool" {
  value = "${google_container_node_pool.tpu_pool.name} (${var.tpu_machine_type}, topology ${var.tpu_topology})"
}
