# Bastion VM — the operator's submission point (reference gke_bastion.tf:57-93).
# Role is unchanged (kubectl + job launch); what it launches changed: instead
# of an out-of-cluster TF chief that carries tensor traffic over per-pod
# LoadBalancers, it only applies manifests and tails logs — the jax
# coordinator runs in-cluster (launch/run_tpu_training_from_bastion.sh).

resource "google_service_account" "bastion_sa" {
  account_id   = "${var.cluster_name}-bastion-sa"
  display_name = "Bastion service account"
}

resource "google_project_iam_member" "bastion_container_dev" {
  project = var.project_id
  role    = "roles/container.developer"
  member  = "serviceAccount:${google_service_account.bastion_sa.email}"
}

resource "google_project_iam_member" "bastion_storage" {
  project = var.project_id
  role    = "roles/storage.objectAdmin"
  member  = "serviceAccount:${google_service_account.bastion_sa.email}"
}

resource "google_compute_firewall" "bastion_ssh" {
  name    = "${var.cluster_name}-bastion-ssh"
  network = google_compute_network.vpc.name

  allow {
    protocol = "tcp"
    ports    = ["22"]
  }
  source_ranges = ["0.0.0.0/0"]
  target_tags   = ["bastion"]
}

resource "google_compute_instance" "bastion" {
  name         = "${var.cluster_name}-bastion"
  machine_type = var.bastion_machine_type
  zone         = var.zone
  tags         = ["bastion"]

  boot_disk {
    initialize_params {
      image = "debian-cloud/debian-12"
    }
  }

  network_interface {
    subnetwork = google_compute_subnetwork.subnet.id
    access_config {} # public IP for operator SSH
  }

  service_account {
    email  = google_service_account.bastion_sa.email
    scopes = ["cloud-platform"]
  }

  metadata_startup_script = templatefile("${path.module}/startup.sh", {
    cluster_name = var.cluster_name
    zone         = var.zone
    project_id   = var.project_id
    bucket       = google_storage_bucket.datasets.name
  })
}
