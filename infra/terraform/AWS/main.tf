# Placeholder: AWS provisioning is not implemented (the reference ships the
# same empty stub, infra/cloud/terraform/AWS/main.tf). TPU hardware is
# GCP-only; an AWS variant would target Trainium and a different runtime.
