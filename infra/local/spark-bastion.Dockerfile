# Spark submit bastion (reference bastion.Dockerfile:1-25): pyspark driver
# environment with the repo's ETL modules and the MySQL JDBC connector.
FROM spark:3.5.1-python3
USER root
RUN pip install --no-cache-dir pyspark==3.5.1 mysql-connector-python pandas numpy
# MySQL Connector/J for the JDBC ingest (reference jars/mysql-connector-j-8.4.0.jar)
ADD https://repo1.maven.org/maven2/com/mysql/mysql-connector-j/8.4.0/mysql-connector-j-8.4.0.jar \
    /opt/spark/jars/
COPY pyspark_tf_gke_tpu /app/pyspark_tf_gke_tpu
ENV PYTHONPATH=/app
WORKDIR /app
