# CPU fake-slice trainer image: same code path as the TPU image, virtual
# 8-device mesh (SURVEY §4 — the kind+MetalLB substitute).
FROM python:3.12-slim
WORKDIR /app
RUN pip install --no-cache-dir jax flax optax orbax-checkpoint einops numpy pillow
COPY pyspark_tf_gke_tpu /app/pyspark_tf_gke_tpu
ENV JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/app
CMD ["python", "-m", "pyspark_tf_gke_tpu.train.cli"]
