"""Execute a chaos schedule against a local fleet while traffic plays.

The runner owns only the PROCESS-LEVEL events (kill/stop/restart);
``inject`` events were already applied at launch via each process's
``--chaos`` flag (:meth:`ChaosSchedule.launch_injections` — the caller
threads them into the fleet's ``replica_args``/``router_args``).

Timing is wall-clock relative to :meth:`ScheduleRunner.start` — start
it at the same instant the replay driver's clock starts, and a
``kill@2.0s`` lands two seconds into the scenario, every run. Each
executed action is recorded (``actions``), emitted on the event trail
(``chaos_action``) and counted (``chaos_actions_total{action}``), so a
scenario can assert its faults actually happened — a chaos run that
injected nothing must fail loudly, not pass vacuously.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from pyspark_tf_gke_tpu.chaos.spec import ChaosEvent, ChaosSchedule
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("chaos.runner")


def _target_indices(target: str, n_replicas: int) -> List[int]:
    idx = target.partition(":")[2]
    if idx == "*":
        return list(range(n_replicas))
    return [int(idx)]


class ScheduleRunner:
    """Background executor for one schedule against one
    ``router/localfleet.LocalFleet``. Use as a context manager around
    the replay call::

        with ScheduleRunner(schedule, fleet):
            report = replay_spec(spec, fleet.url, ...)
        acted = runner.actions  # what actually fired, with wall times

    Exit joins the thread (remaining events run to completion — a
    scheduled SIGCONT must never be skipped or a replica stays frozen)
    and SIGCONTs/restarts anything the schedule left down unless
    ``heal_on_exit=False``.
    """

    def __init__(self, schedule: ChaosSchedule, fleet,
                 speedup: float = 1.0, heal_on_exit: bool = True):
        if speedup <= 0:
            raise ValueError("speedup must be > 0")
        self.schedule = schedule.validate()
        self.fleet = fleet
        self.speedup = float(speedup)
        self.heal_on_exit = bool(heal_on_exit)
        self.actions: List[dict] = []
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._t0: Optional[float] = None
        self._abort = threading.Event()
        self._stopped: set = set()   # replica idx currently SIGSTOPped
        self._killed: set = set()    # replica idx killed, not restarted

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "ScheduleRunner":
        self._t0 = time.monotonic()
        self._thread = threading.Thread(target=self._run,
                                        name="chaos-runner", daemon=True)
        self._thread.start()
        return self

    def join(self, timeout_s: float = 120.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def __enter__(self) -> "ScheduleRunner":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.join()
        if self._thread is not None and self._thread.is_alive():
            # a schedule with events far past the traffic window must
            # not keep mutating the fleet (or self.actions) after the
            # context exits — abort the remainder; heal() below takes
            # over the SIGCONTs/restarts the aborted tail owed
            self._abort.set()
            self._thread.join(timeout=10)
        if self.heal_on_exit:
            self.heal()

    def heal(self) -> None:
        """Bring every schedule-downed replica back (SIGCONT + restart)
        so post-scenario invariant checks see a live fleet."""
        for i in sorted(self._stopped):
            try:
                self.fleet.cont_replica(i)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        self._stopped.clear()
        for i in sorted(self._killed):
            try:
                self.fleet.restart_replica(i)
                self._note("restart", f"replica:{i}", healed=True)
            except Exception:  # noqa: BLE001
                logger.exception("heal restart of replica %d failed", i)
        self._killed.clear()

    # -- execution --------------------------------------------------------

    def _note(self, action: str, target: str, **extra) -> None:
        rec = {"action": action, "target": target,
               "at_s": round(time.monotonic() - self._t0, 3), **extra}
        with self._lock:
            self.actions.append(rec)
        try:
            from pyspark_tf_gke_tpu.obs.events import get_event_log
            from pyspark_tf_gke_tpu.obs.metrics import chaos_families

            chaos_families()["chaos_actions_total"].labels(
                action=action).inc()
            get_event_log().emit("chaos_action", **rec)
        except Exception:  # noqa: BLE001 — accounting must not stop
            pass           # the chaos

    def _run(self) -> None:
        pending: List[tuple] = []  # (due_s, seq, fn) — seq breaks ties
        seq = 0
        for ev in self.schedule.process_events():
            pending.append((ev.offset_s / self.speedup, seq,
                            self._make_action(ev)))
            seq += 1
            # a kill with restart_s schedules its own relaunch; a stop
            # schedules its SIGCONT — both as first-class entries so
            # join() can never exit with a replica frozen mid-schedule
            if ev.action == "kill" and ev.restart_s is not None:
                pending.append((
                    (ev.offset_s + ev.restart_s) / self.speedup, seq,
                    self._make_restart(ev)))
                seq += 1
            if ev.action == "stop":
                pending.append((
                    (ev.offset_s + ev.duration_s) / self.speedup, seq,
                    self._make_cont(ev)))
                seq += 1
        pending.sort(key=lambda p: (p[0], p[1]))
        for due_s, _, fn in pending:
            delay = self._t0 + due_s - time.monotonic()
            if delay > 0 and self._abort.wait(delay):
                return  # context exited: heal() owns the cleanup
            if self._abort.is_set():
                return
            try:
                fn()
            except Exception:  # noqa: BLE001 — one failed action must
                logger.exception("chaos action failed")  # not end the run

    def _make_action(self, ev: ChaosEvent):
        def act():
            for i in _target_indices(ev.target, self.fleet.n_replicas):
                if ev.action == "kill":
                    self.fleet.kill_replica(i)
                    self._killed.add(i)
                    self._note("kill", f"replica:{i}")
                elif ev.action == "stop":
                    self.fleet.stop_replica(i)
                    self._stopped.add(i)
                    self._note("stop", f"replica:{i}",
                               duration_s=ev.duration_s)
                elif ev.action == "restart":
                    self.fleet.restart_replica(i)
                    self._killed.discard(i)
                    self._note("restart", f"replica:{i}")
        return act

    def _make_restart(self, ev: ChaosEvent):
        def act():
            for i in _target_indices(ev.target, self.fleet.n_replicas):
                self.fleet.restart_replica(i)
                self._killed.discard(i)
                self._note("restart", f"replica:{i}")
        return act

    def _make_cont(self, ev: ChaosEvent):
        def act():
            for i in _target_indices(ev.target, self.fleet.n_replicas):
                self.fleet.cont_replica(i)
                self._stopped.discard(i)
                self._note("cont", f"replica:{i}")
        return act
