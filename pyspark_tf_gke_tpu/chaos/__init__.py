"""System-wide deterministic fault injection (the chaos plane).

The train plane had the only real fault injector (PR 3 lifted it into
the serving driver loop); everything else — the router's transport and
health prober, the BundleServer request front, the engine's device
steps, checkpoint IO, the pipeline's publish path — was tested on
sunny-day paths only. This package is the shared layer:

* :mod:`~pyspark_tf_gke_tpu.chaos.inject` — named fault points +
  seed-deterministic injectors (``ChaosInjector``), a process-global
  install, and the lifted train-plane :class:`FaultInjector`;
* :mod:`~pyspark_tf_gke_tpu.chaos.spec` — the versioned chaos-schedule
  spec (sibling of ``replay/spec.py``): scheduled process-level
  kill/stop/restart actions plus launch-time in-process injections;
* :mod:`~pyspark_tf_gke_tpu.chaos.runner` — executes a schedule against
  a ``router/localfleet.py`` fleet while a replay drives traffic
  (``tools/replay.py run --chaos``);
* :mod:`~pyspark_tf_gke_tpu.chaos.invariants` — the post-scenario
  checker: every submitted request reached exactly one terminal
  outcome, zero stuck slots, KV-page refcounts and pool occupancy back
  at baseline.

Everything here is stdlib-only and jax-free: the router and the replay
driver import it without a device runtime.
"""

from pyspark_tf_gke_tpu.chaos.inject import (  # noqa: F401
    FAULT_POINTS,
    ChaosInjector,
    FaultInjector,
    InjectedFault,
    chaos_fire,
    get_injector,
    install,
    uninstall,
)
