"""Post-scenario invariant checker: the durability contract, verified.

THE invariant every chaos scenario must close on: **every submitted
request reaches exactly one terminal outcome (ok | shed | deadline |
error | cancelled) under any single injected fault** — no silent
drops, no double terminals — and the serving state returns to
baseline: zero stuck slots, the KV page pool fully accounted (every
refcount owned by a live slot, an in-flight admission or the radix
trie; free + referenced == total; no page both free and referenced),
no admission wedged mid-flight.

Four check surfaces, composable:

* :func:`check_engine` / :func:`check_front` — in-process, against a
  live (quiesced) ``ContinuousEngine`` / ``_ContinuousFront``: the
  refcount discipline audited directly (tests drive faults and then
  call these; a DELIBERATELY leaked ref must fail — the checker has
  true-positive tests of its own).
* :func:`check_replica` — over HTTP against a live replica
  (``/loadz`` + ``/healthz``): the post-scenario gate
  ``tools/replay.py run --chaos`` and ``smoke_check --chaos`` apply to
  every surviving replica.
* :func:`check_traces` — over a ``/traces`` export (the PR 9 flight
  recorder): every request span carries EXACTLY one terminal verdict
  (a ``terminal`` event, or a ``shed`` event for requests the
  admission gates turned away).
* :func:`check_report` — over a replay report: one terminal outcome
  per replayed request, client-side.

Every function returns ``{"ok": bool, "violations": [str, ...]}`` and
never raises on malformed input — a checker that crashes mid-scenario
reads as a pass to a shell ``&&`` chain.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Dict, List

# the complete terminal vocabulary: ok/deadline from the engine's state
# transitions, shed from the admission gates, error from rebuild /
# watchdog / transport paths, cancelled from client abandonment
TERMINAL_OUTCOMES = ("ok", "shed", "deadline", "error", "cancelled")


def _result(violations: List[str], **extra) -> dict:
    return {"ok": not violations, "violations": violations, **extra}


# -- in-process ---------------------------------------------------------------


def check_engine(engine) -> dict:
    """Baseline invariants of a quiesced ``ContinuousEngine``.

    Call after the scenario drains (queue empty, no live requests):
    anything still occupied is a stuck slot / wedged admission, and the
    page-pool accounting must balance to the page regardless of which
    crash paths ran."""
    v: List[str] = []
    try:
        if engine._queue:
            v.append(f"{len(engine._queue)} request(s) stuck in the "
                     "admission queue")
        if engine._slots:
            v.append(f"stuck slot(s): {sorted(engine._slots)}")
        if engine._admitting is not None:
            v.append("piecewise admission wedged in flight "
                     f"(rid {engine._admitting['req'].rid})")
        if engine._inflight_q:
            v.append(f"{len(engine._inflight_q)} dispatched chunk(s) "
                     "never collected")
        if not engine.paged:
            return _result(v)
        total = engine.model.cfg.kv_num_pages
        refs = dict(engine._page_refs)
        free = list(engine._free_pages)
        # expected refcounts: one per page per owner (slot pages, the
        # trie's indexed pages; a quiesced engine has no admission
        # holds left)
        expected: Dict[int, int] = {}
        for pages in engine._slot_pages.values():
            for p in pages:
                expected[p] = expected.get(p, 0) + 1
        if engine.radix is not None:
            for p in engine.radix.indexed_pages():
                expected[p] = expected.get(p, 0) + 1
        if refs != expected:
            extra = {p: n for p, n in refs.items()
                     if n != expected.get(p, 0)}
            missing = {p: n for p, n in expected.items()
                       if n != refs.get(p, 0)}
            v.append(f"page refcounts off baseline: held={extra} "
                     f"expected={missing}")
        leaked = set(free) & set(refs)
        if leaked:
            v.append(f"page(s) both free and referenced: "
                     f"{sorted(leaked)}")
        if len(free) != len(set(free)):
            v.append("duplicate pages on the free list")
        if len(set(free)) + len(refs) != total and not leaked:
            v.append(f"pages lost: {len(set(free))} free + "
                     f"{len(refs)} referenced != {total} total")
        cache_pages = (engine.radix.resident_pages
                       if engine.radix is not None else 0)
        in_use = total - len(set(free))
        if in_use != cache_pages and refs == expected and not leaked:
            v.append(f"pool occupancy off baseline: {in_use} in use "
                     f"but only {cache_pages} cache-resident")
    except Exception as exc:  # noqa: BLE001 — a checker crash must be
        v.append(f"checker error: {type(exc).__name__}: {exc}")  # loud
    return _result(v)


def check_front(front) -> dict:
    """Engine invariants + the front's waiter table: no request handle
    left undelivered (a waiter with no result and no terminal is a
    silent drop in progress)."""
    out = check_engine(front.engine)
    v = list(out["violations"])
    try:
        pending = [rid for rid, slot in front._results.items()
                   if slot[1] is None and not slot[0].is_set()]
        if pending:
            v.append(f"undelivered waiter(s): {pending}")
    except Exception as exc:  # noqa: BLE001
        v.append(f"checker error: {type(exc).__name__}: {exc}")
    return _result(v)


# -- over HTTP ----------------------------------------------------------------


def check_replica(base_url: str, timeout_s: float = 10.0) -> dict:
    """Post-scenario gate against a LIVE replica: quiesced queue/slots,
    pool occupancy equal to the prefix cache's residency (pages held
    only by the trie), no wedged admission. Uses only /loadz +
    /healthz — the same surfaces the router scores on."""
    v: List[str] = []
    base_url = base_url.rstrip("/")
    try:
        with urllib.request.urlopen(base_url + "/loadz",
                                    timeout=timeout_s) as resp:
            lz = json.loads(resp.read())
        with urllib.request.urlopen(base_url + "/healthz",
                                    timeout=timeout_s) as resp:
            hz = json.loads(resp.read())
    except Exception as exc:  # noqa: BLE001
        return _result([f"replica unreachable: "
                        f"{type(exc).__name__}: {exc}"], url=base_url)
    if lz.get("queued"):
        v.append(f"{lz['queued']} request(s) stuck queued")
    if lz.get("active"):
        v.append(f"{lz['active']} stuck slot(s)")
    stats = hz.get("continuous") or {}
    if stats.get("admitting") is not None:
        v.append(f"admission wedged (rid {stats['admitting']})")
    if stats.get("inflight"):
        v.append("dispatched chunk(s) never collected")
    paged = stats.get("paged")
    if paged:
        cache = stats.get("prefix_cache") or {}
        resident = int(cache.get("resident_pages", 0))
        in_use = int(paged.get("pages_in_use", 0))
        if in_use != resident:
            v.append(f"pool occupancy off baseline: {in_use} pages in "
                     f"use, {resident} cache-resident")
    return _result(v, url=base_url)


# -- over the flight recorder -------------------------------------------------


def _iter_traces(traces):
    """Accept a /traces JSON body ({"traces": [...]}), a bare list, or
    a jsonl bytes/str export — one dict per trace either way."""
    if isinstance(traces, (bytes, str)):
        text = traces.decode() if isinstance(traces, bytes) else traces
        out = []
        for line in text.splitlines():
            line = line.strip()
            if line:
                out.append(json.loads(line))
        return out
    if isinstance(traces, dict):
        return list(traces.get("traces") or [])
    return list(traces or [])


def check_traces(traces) -> dict:
    """Exactly one terminal verdict per REQUEST SPAN.

    A request span is any span carrying the replay shape contract
    (``prompt_tokens`` attr — stamped by the serve front before the
    admission gates and by the engine at submit, so shed demand counts
    too). Its verdict is a ``terminal`` event (engine state
    transitions: ok | deadline | error | cancelled) or a ``shed``
    event (admission gates). Zero verdicts = a silent drop; more than
    one = a double delivery. Canary (``__internal__``) spans are
    exempt from the shed check but still must not double-terminal."""
    v: List[str] = []
    checked = 0
    try:
        for trace in _iter_traces(traces):
            for span in trace.get("spans") or []:
                attrs = span.get("attrs") or {}
                if "prompt_tokens" not in attrs:
                    continue
                checked += 1
                terminals = [e for e in span.get("events") or []
                             if e.get("name") == "terminal"]
                sheds = [e for e in span.get("events") or []
                         if e.get("name") == "shed"]
                tid = trace.get("trace_id", "?")
                n = len(terminals) + len(sheds)
                if n == 0:
                    v.append(f"trace {tid}: request span has NO "
                             "terminal verdict (silent drop)")
                elif n > 1:
                    v.append(
                        f"trace {tid}: request span has {n} terminal "
                        f"verdicts ({[e['name'] for e in terminals]} + "
                        f"{len(sheds)} shed)")
                for e in terminals:
                    if e.get("outcome") not in TERMINAL_OUTCOMES:
                        v.append(f"trace {tid}: unknown terminal "
                                 f"outcome {e.get('outcome')!r}")
    except Exception as exc:  # noqa: BLE001
        v.append(f"checker error: {type(exc).__name__}: {exc}")
    return _result(v, request_spans=checked)


# -- stream token-exactness ---------------------------------------------------


def check_stream_tokens(expected, received) -> dict:
    """THE stream-splice invariant: a client stream that crossed a
    replica kill must be token-identical to an uninterrupted control
    run — **zero missing and zero duplicated tokens**.

    ``expected`` is the control run's token-id sequence, ``received``
    the assembled sequence a client captured through the failover.
    Violations CLASSIFY the failure (the diagnosis a splice bug needs):
    a duplicated run at the splice point (overlap not stripped), a
    missing run (off-by-one the other way), a truncated tail, extra
    tokens past the control, or outright divergence. Deliberately
    broken splices must FAIL here — the checker has true-positive
    tests of its own."""
    v: List[str] = []
    try:
        e = [int(t) for t in expected]
        g = [int(t) for t in received]
    except (TypeError, ValueError) as exc:
        return _result([f"checker error: unparseable token ids: {exc}"])
    if g == e:
        return _result([], tokens=len(e))
    i = next((k for k in range(min(len(e), len(g))) if e[k] != g[k]),
             min(len(e), len(g)))
    if len(g) < len(e) and g == e[:len(g)]:
        v.append(f"{len(e) - len(g)} token(s) missing from the stream "
                 f"tail (got {len(g)} of {len(e)})")
    elif len(g) > len(e) and g[:len(e)] == e:
        v.append(f"{len(g) - len(e)} extra token(s) past the control "
                 f"run (got {len(g)}, expected {len(e)})")
    else:
        classified = False
        for k in range(1, 5):
            # duplicated run: the stream re-emitted the k tokens
            # before the splice (g = e[:i] + e[i-k:i] + e[i:], so the
            # received suffix equals the control suffix shifted BACK)
            if i >= k and g[i:] == e[i - k:]:
                v.append(f"{k} duplicated token(s) at offset {i} "
                         "(splice overlap not stripped)")
                classified = True
                break
            # missing run: k tokens skipped at the splice (suffix
            # shifted FORWARD)
            if g[i:] == e[i + k:]:
                v.append(f"{k} missing token(s) at offset {i} "
                         "(splice skipped past the emitted point)")
                classified = True
                break
        if not classified:
            v.append(f"stream diverges at offset {i}: expected "
                     f"{e[i:i + 4]}, got {g[i:i + 4]}")
    return _result(v, tokens=len(e))


def check_stream_report(report: dict) -> dict:
    """Client-side stream durability over a replay report: every
    streamed request reached ``[DONE]`` — no EOF-without-terminator
    (the signature of an unspliced mid-stream death) and no transport
    errors. Windowed goodput is :func:`goodput_windows`'s job; this is
    the absolute zero-lost-streams gate."""
    v: List[str] = []
    try:
        for r in report.get("requests") or []:
            if r.get("reason") == "eof_without_done":
                v.append(f"request {r.get('i')}: stream ended without "
                         "[DONE] (mid-stream death reached the client)")
            elif r.get("outcome") == "error":
                v.append(f"request {r.get('i')}: error terminal "
                         f"({r.get('reason')})")
        if not (report.get("requests") or []):
            v.append("report carries no per-request records "
                     "(include_requests=True required)")
    except Exception as exc:  # noqa: BLE001
        v.append(f"checker error: {type(exc).__name__}: {exc}")
    return _result(v)


# -- over a replay report -----------------------------------------------------


def check_report(report: dict, n_expected: int) -> dict:
    """Client-side closure: every replayed request reached exactly one
    terminal outcome (the driver's accounting sums to the spec)."""
    v: List[str] = []
    try:
        outcomes = dict(report.get("outcomes") or {})
        total = sum(outcomes.values())
        if total != n_expected:
            v.append(f"{n_expected - total} request(s) never reached a "
                     f"terminal outcome (outcomes: {outcomes})")
        unknown = set(outcomes) - set(TERMINAL_OUTCOMES)
        if unknown:
            v.append(f"unknown outcome class(es): {sorted(unknown)}")
    except Exception as exc:  # noqa: BLE001
        v.append(f"checker error: {type(exc).__name__}: {exc}")
    return _result(v)


def goodput_windows(report: dict, edges: List[float]) -> List[dict]:
    """Windowed ok-rate over a replay report's per-request records
    (requires ``include_requests=True``): requests bucketed by their
    spec offset into ``[edges[i], edges[i+1])`` windows — the
    goodput-recovery read a replica-kill scenario asserts on (ok-rate
    before the kill, through it, after the restart)."""
    reqs = report.get("requests") or []
    out = []
    for lo, hi in zip(edges, edges[1:]):
        win = [r for r in reqs
               if r.get("offset_s") is not None
               and lo <= float(r["offset_s"]) < hi]
        ok = sum(1 for r in win if r.get("outcome") == "ok")
        out.append({"from_s": lo, "to_s": hi, "requests": len(win),
                    "ok": ok,
                    "ok_rate": round(ok / len(win), 4) if win else None})
    return out
