"""Named fault points + seed-deterministic injectors.

Two injector shapes share one exception type:

* :class:`FaultInjector` — the train plane's step-loop injector,
  LIFTED here from ``train/resilience.py`` (which re-exports it for
  every existing caller): fail/slow at chosen global steps, fired
  once each, so the recovery path is *tested*, not assumed.
* :class:`ChaosInjector` — the system-wide generalization: rules bind
  to NAMED fault points (:data:`FAULT_POINTS`) wired through the
  router transport, the health prober, the BundleServer request front,
  the engine's device dispatch, checkpoint IO and the pipeline publish
  path. Rules fire by per-point invocation count (``point:fail@N`` —
  exactly reproducible) or by seeded probability (``point:fail%P`` —
  the same seed fires the same invocation set, every run, every
  machine: the RNG is a private splitmix64 stream keyed on
  ``(seed, point, rule index)``, nothing environmental feeds it).

Instrumented sites call :func:`chaos_fire` — one module-global ``None``
check when no injector is installed, so production hot paths pay a
single attribute load. Every fired fault lands on the event trail
(``fault_injected``) and the ``fault_injections_total{point,action}``
counter, so a chaos run's injections and the recoveries they forced
correlate by seq.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Mapping, Optional

# -- the fault-point catalog (docs/CHAOS.md mirrors this) ---------------------
#
# A rule naming a point not listed here is a spec error (fail fast at
# parse time — a typo'd point would otherwise silently never fire and
# the scenario would "pass" having injected nothing).
FAULT_POINTS: Dict[str, str] = {
    # router data plane: one forwarded POST raises ReplicaUnreachable
    # before the status line — the passive-health + single-failover
    # path (a scheduled stand-in for a pod dying mid-connect)
    "router.transport": "forwarded replica request transport failure",
    # router control plane: one /loadz probe raises — the health-flap /
    # probe-partition shape (the replica is fine; the prober can't see
    # it, so fail-threshold and re-admission logic must carry it)
    "router.probe": "health-probe transport failure (probe partition)",
    # BundleServer HTTP front: the request handler raises after the
    # body parse — the 500-with-terminal path, counted, never a hang
    "serve.request": "BundleServer request-front failure",
    # engine device plane: raise (failed device step -> engine rebuild)
    # or sleep (hung device step -> the step watchdog's case) inside
    # the decode-chunk dispatch, while the driver loop holds its lock
    "engine.device_step": "failed/hung device decode-chunk dispatch",
    # engine admission: raise after the page allocation, before the
    # prefill lands — the refcount-discipline crash path (held pages
    # must return to the pool; the request must stay queued or fail
    # with a terminal, never leak)
    "engine.admit": "admission failure after page allocation",
    # checkpoint IO: raise inside the retried save/restore closures so
    # the injection exercises retry_with_backoff, not a bare raise
    "checkpoint.save": "checkpoint save IO failure (inside the retry)",
    "checkpoint.restore": "checkpoint restore IO failure (inside the retry)",
    # serving-bundle load (boot + hot-swap reload, same retried path)
    "bundle.load": "serving-bundle load failure (inside the retry)",
    # pipeline publish: one POST /admin/reload raises — the rollout
    # must stop (untouched replicas keep serving) and the coordinator
    # must resume the publish stage on its next round entry
    "pipeline.publish": "replica publish (POST /admin/reload) failure",
    # autopilot actuation: one scale-up/scale-down application raises
    # before the action takes effect — the decision must retry with
    # backoff and apply EXACTLY once (never double-started, never
    # double-drained), which the autopilot tests assert in closed form
    "autopilot.actuate": "autopilot scale actuation failure",
}

_ACTIONS = ("fail", "slow", "hang")


class InjectedFault(RuntimeError):
    """Raised by the injectors — distinguishable from real faults."""


def _rule_stream(seed: int, point: str, index: int):
    """Deterministic U[0,1) stream for one probabilistic rule — keyed
    on (seed, point, rule index) over the shared replay/chaos mixer
    (``replay/spec.py`` ``seeded_unit_stream``) so NOTHING
    environmental (hash randomization, process ids, wall clock) can
    change which invocations fire."""
    from pyspark_tf_gke_tpu.replay.spec import seeded_unit_stream

    return seeded_unit_stream(f"{seed}:{point}:{index}")


class _Rule:
    """One parsed injection rule bound to a fault point."""

    __slots__ = ("point", "action", "at", "prob", "seconds", "max_fires",
                 "fires", "_stream")

    def __init__(self, point: str, action: str, *, at: Optional[int] = None,
                 prob: Optional[float] = None, seconds: float = 0.0,
                 max_fires: Optional[int] = None, seed: int = 0,
                 index: int = 0):
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r} (known: "
                f"{', '.join(sorted(FAULT_POINTS))})")
        if action not in _ACTIONS:
            raise ValueError(
                f"unknown action {action!r} (known: {_ACTIONS})")
        if (at is None) == (prob is None):
            raise ValueError(
                f"rule on {point!r} needs exactly one of @N / %P")
        if at is not None and at < 1:
            raise ValueError(f"rule on {point!r}: @N is 1-based")
        if prob is not None and not 0.0 < prob <= 1.0:
            raise ValueError(
                f"rule on {point!r}: %P must be in (0, 1], got {prob}")
        if action in ("slow", "hang") and seconds <= 0:
            raise ValueError(
                f"rule on {point!r}: {action} takes :SECONDS > 0")
        self.point = point
        self.action = action
        self.at = at
        self.prob = prob
        self.seconds = float(seconds)
        # count-based rules fire ONCE (the train injector's fired-once
        # contract: a post-recovery replay of the same step must not
        # immediately re-fail); probabilistic rules default unbounded
        self.max_fires = (max_fires if max_fires is not None
                          else (1 if at is not None else None))
        self.fires = 0
        self._stream = (_rule_stream(seed, point, index)
                        if prob is not None else None)

    def should_fire(self, invocation: int) -> bool:
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.at is not None:
            return invocation == self.at
        # probabilistic: ONE draw per invocation, consumed whether or
        # not it fires, so the fired set depends only on (seed, point,
        # rule index, invocation number)
        return next(self._stream) < self.prob

    def describe(self) -> str:
        when = (f"@{self.at}" if self.at is not None
                else f"%{self.prob:g}")
        dur = f":{self.seconds:g}" if self.seconds else ""
        cap = (f"x{self.max_fires}"
               if self.max_fires is not None and self.at is None else "")
        return f"{self.point}:{self.action}{when}{dur}{cap}"


class ChaosInjector:
    """Seed-deterministic injector over named fault points.

    Spec grammar (comma-separated tokens)::

        POINT:ACTION@N[:SECONDS]        fire at the Nth hit of POINT (once)
        POINT:ACTION%P[:SECONDS][xK]    fire each hit w.p. P (seeded; at
                                        most K times when xK is given)
        seed=S                          seed for the %P streams

    Actions: ``fail`` raises (:class:`InjectedFault`, or the exception
    type the call site maps it to — e.g. the router maps to
    ``ReplicaUnreachable`` so the REAL handling path runs), ``slow``
    and ``hang`` sleep SECONDS (two spellings of one mechanic; ``hang``
    documents intent — it is the shape a step watchdog must reap).

    Thread-safe: fired from HTTP handler threads, the prober and the
    engine driver concurrently; per-point invocation counters and rule
    state live behind one lock (the sleep itself runs outside it).
    """

    def __init__(self, rules: Iterable[_Rule], seed: int = 0):
        self.rules: List[_Rule] = list(rules)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._invocations: Dict[str, int] = {}
        # (point, action, invocation) of every fired rule — the
        # post-run accounting a chaos scenario asserts on
        self.fired: List[dict] = []

    @classmethod
    def from_spec(cls, spec: str) -> Optional["ChaosInjector"]:
        """Parse the spec grammar; empty → None (no injection)."""
        tokens = [t.strip() for t in str(spec).split(",") if t.strip()]
        seed = 0
        raw: List[str] = []
        for tok in tokens:
            if tok.startswith("seed="):
                seed = int(tok[len("seed="):])
            else:
                raw.append(tok)
        rules: List[_Rule] = []
        for i, tok in enumerate(raw):
            point, sep, rest = tok.partition(":")
            if not sep or not point or not rest:
                raise ValueError(
                    f"chaos token {tok!r}: want POINT:ACTION@N or "
                    f"POINT:ACTION%P (see FAULT_POINTS)")
            action = rest
            at = prob = None
            seconds = 0.0
            max_fires = None
            if "@" in rest:
                action, _, where = rest.partition("@")
                where, _, dur = where.partition(":")
                at = int(where)
                seconds = float(dur) if dur else 0.0
            elif "%" in rest:
                action, _, p = rest.partition("%")
                if "x" in p:
                    p, _, cap = p.rpartition("x")
                    max_fires = int(cap)
                p, _, dur = p.partition(":")
                prob = float(p)
                seconds = float(dur) if dur else 0.0
            else:
                raise ValueError(
                    f"chaos token {tok!r}: ACTION needs @N or %P")
            rules.append(_Rule(point, action, at=at, prob=prob,
                               seconds=seconds, max_fires=max_fires,
                               seed=seed, index=i))
        if not rules:
            return None
        return cls(rules, seed=seed)

    def describe(self) -> str:
        out = ",".join(r.describe() for r in self.rules)
        return f"seed={self.seed},{out}" if self.seed else out

    def fired_count(self, point: Optional[str] = None) -> int:
        with self._lock:
            if point is None:
                return len(self.fired)
            return sum(1 for f in self.fired if f["point"] == point)

    def fire(self, point: str, exc: Optional[type] = None, **ctx):
        """One hit of ``point``: advance its invocation counter, fire
        any due rules. A ``fail`` rule raises ``exc`` (default
        :class:`InjectedFault`) AFTER any due slow/hang sleeps run —
        scheduled latency composes with scheduled failure. Returns the
        injected sleep seconds (0.0 when nothing slowed)."""
        due: List[_Rule] = []
        with self._lock:
            n = self._invocations.get(point, 0) + 1
            self._invocations[point] = n
            for rule in self.rules:
                if rule.point == point and rule.should_fire(n):
                    rule.fires += 1
                    due.append(rule)
            for rule in due:
                self.fired.append({"point": point, "action": rule.action,
                                   "invocation": n,
                                   "seconds": rule.seconds, **ctx})
        if not due:
            return 0.0
        slept = 0.0
        failing: Optional[_Rule] = None
        for rule in due:
            self._note(point, rule.action, n, rule.seconds, ctx)
            if rule.action == "fail":
                failing = rule
            else:
                time.sleep(rule.seconds)
                slept += rule.seconds
        if failing is not None:
            exc_type = exc if exc is not None else InjectedFault
            raise exc_type(
                f"injected fault at {point} (invocation {n})")
        return slept

    @staticmethod
    def _note(point: str, action: str, invocation: int, seconds: float,
              ctx: dict) -> None:
        """Trail event + counter for one fired rule. Lazy obs import:
        this module is on the router/client hot path and must stay
        import-cheap; a broken obs plane must never mask the fault."""
        try:
            from pyspark_tf_gke_tpu.obs.events import get_event_log
            from pyspark_tf_gke_tpu.obs.metrics import chaos_families

            chaos_families()["fault_injections_total"].labels(
                point=point, action=action).inc()
            get_event_log().emit(
                "fault_injected", point=point, action=action,
                invocation=invocation,
                **({"seconds": seconds} if seconds else {}),
                **{k: str(v)[:120] for k, v in ctx.items()})
        except Exception:  # noqa: BLE001 — observability of the chaos
            pass           # must never change what the chaos does


# -- process-global install ---------------------------------------------------
#
# One injector per process (a replica, a router, a coordinator each get
# their own via --chaos / SERVE_CHAOS / ROUTER_CHAOS). Module-global so
# instrumented sites pay a single attribute load when chaos is off —
# which is every production process, always.

_INJECTOR: Optional[ChaosInjector] = None


def install(injector: Optional[ChaosInjector]) -> Optional[ChaosInjector]:
    """Install ``injector`` as the process's fault source (None clears
    it). Returns the previous injector so tests can restore it."""
    global _INJECTOR
    prev = _INJECTOR
    _INJECTOR = injector
    return prev


def uninstall() -> None:
    install(None)


def get_injector() -> Optional[ChaosInjector]:
    return _INJECTOR


def chaos_fire(point: str, exc: Optional[type] = None, **ctx):
    """THE instrumented-site entry: no-op (one None check) without an
    installed injector; otherwise one hit of ``point``."""
    if _INJECTOR is None:
        return 0.0
    return _INJECTOR.fire(point, exc=exc, **ctx)


# -- the lifted train-plane injector ------------------------------------------


class FaultInjector:
    """Deterministic chaos for a STEP LOOP: raise :class:`InjectedFault`
    when the loop reaches any of ``fail_at_steps`` — once per step
    value, so the post-recovery pass (which replays the same global
    step after resume) does not immediately re-fail. ``slow_at_steps``
    (step → seconds) injects SLOW steps instead of failures — the
    wedged-device shape a liveness probe must catch — each fired once
    as well.

    Lifted from ``train/resilience.py`` (which re-exports it): the
    trainer's recovery loop and the serving driver loop (``--chaos``
    ``fail@N``/``slow@N:S`` tokens) both ride this; the named-point
    :class:`ChaosInjector` generalizes the same mechanics to the rest
    of the system."""

    def __init__(self, fail_at_steps: Iterable[int] = (),
                 slow_at_steps: Optional[Mapping[int, float]] = None):
        self.pending = set(int(s) for s in fail_at_steps)
        self.slow_pending: Dict[int, float] = {
            int(k): float(v) for k, v in (slow_at_steps or {}).items()}
        # the injection plan, for post-run accounting (a chaos soak
        # asserts rebuilds == faults that actually fired)
        self.n_faults = len(self.pending)
        self.n_slow = len(self.slow_pending)

    @classmethod
    def from_spec(cls, spec: str) -> Optional["FaultInjector"]:
        """Parse a "12,40" CLI/env spec; empty → None (no injection)."""
        steps = [int(s) for s in spec.split(",") if s.strip()]
        return cls(steps) if steps else None

    @classmethod
    def from_chaos_spec(cls, spec: str) -> Optional["FaultInjector"]:
        """Parse the serve-side chaos spec: comma-separated tokens
        ``fail@STEP`` (raise at driver step STEP) and
        ``slow@STEP:SECONDS`` (sleep SECONDS at that step); a bare
        integer is a failure (the training spec's shorthand). Empty →
        None (no injection). ``SERVE_CHAOS="fail@10,slow@25:0.5"``
        fails the 10th busy driver iteration and wedges the 25th.
        (Named-point tokens — anything with a ``.`` before the first
        ``:`` — belong to :meth:`ChaosInjector.from_spec`; the serve
        CLI splits the two grammars.)"""
        fails, slows = [], {}
        for tok in spec.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if tok.startswith("slow@"):
                where, _, dur = tok[len("slow@"):].partition(":")
                if not where or not dur:
                    raise ValueError(
                        f"chaos token {tok!r}: slow takes "
                        f"slow@STEP:SECONDS")
                slows[int(where)] = float(dur)
            elif tok.startswith("fail@"):
                fails.append(int(tok[len("fail@"):]))
            else:
                fails.append(int(tok))
        if not fails and not slows:
            return None
        return cls(fails, slows)

    @property
    def fired_faults(self) -> int:
        """Failures injected so far (plan minus still-pending)."""
        return self.n_faults - len(self.pending)

    def maybe_fail(self, step: int) -> None:
        if int(step) in self.pending:
            self.pending.discard(int(step))
            from pyspark_tf_gke_tpu.obs.events import get_event_log

            # preemption-simulation evidence rides the shared trail: a
            # chaos run's injected faults and its retries correlate by seq
            get_event_log().emit("fault_injected", step=int(step))
            raise InjectedFault(f"injected fault at step {step}")

    def maybe_slow(self, step: int) -> float:
        """Sleep (once) if ``step`` is a planned slow step; returns the
        injected delay in seconds (0.0 when none fired)."""
        dur = self.slow_pending.pop(int(step), None)
        if not dur:
            return 0.0
        from pyspark_tf_gke_tpu.obs.events import get_event_log

        get_event_log().emit("slow_step_injected", step=int(step),
                             seconds=float(dur))
        time.sleep(dur)
        return float(dur)


def split_serve_chaos_spec(spec: str):
    """Split one ``--chaos`` value into its two grammars: legacy
    driver-loop tokens (``fail@N`` / ``slow@N:S`` / bare ints →
    :class:`FaultInjector`) and named-point tokens (``POINT:ACTION...``
    where POINT contains a ``.`` → :class:`ChaosInjector`). Returns
    ``(fault_injector_or_None, chaos_injector_or_None)``."""
    legacy, named = [], []
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        head = tok.partition(":")[0]
        if "." in head or tok.startswith("seed="):
            named.append(tok)
        else:
            legacy.append(tok)
    return (FaultInjector.from_chaos_spec(",".join(legacy))
            if legacy else None,
            ChaosInjector.from_spec(",".join(named)) if named else None)
