"""The versioned chaos-schedule spec: one JSONL file = one scenario.

Sibling of ``replay/spec.py`` (same header-line + one-event-per-line
shape, same sorted-offset discipline) so a chaos scenario composes with
a workload spec: ``tools/replay.py run --chaos chaos.jsonl`` drives the
replay clock and this schedule against the SAME local fleet, killing /
stopping / restarting replicas at scheduled offsets while the workload
plays.

Two event classes:

* **Process-level** (``kill`` / ``stop`` / ``restart``) — executed by
  :mod:`~pyspark_tf_gke_tpu.chaos.runner` against a
  ``router/localfleet.py`` fleet at their ``offset_s``. ``stop`` is
  SIGSTOP for ``duration_s`` then SIGCONT: the local stand-in for both
  a hung host AND a network partition (the process is alive but
  unreachable — probes time out, streams stall). ``kill`` is SIGKILL;
  ``restart_s`` relaunches the replica that many seconds later (the
  goodput-recovery proof).
* **In-process** (``inject``) — a :class:`ChaosInjector` spec string
  applied AT LAUNCH via the target's ``--chaos`` flag (offset must be
  0: count-based rules are the deterministic mechanism inside a
  process; the schedule cannot reach into a running one). Targets:
  ``replica:N`` / ``replica:*`` / ``router``.

Determinism: :func:`synth_chaos` derives every offset from an explicit
seeded mixer — same seed ⇒ byte-identical schedule ⇒ same fired
faults, which is what makes a chaos run a regression test instead of a
dice roll.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from pyspark_tf_gke_tpu.chaos.inject import ChaosInjector

SCHEDULE_KIND = "pyspark_tf_gke_tpu.chaos_schedule"
SCHEDULE_VERSION = 1

_ACTIONS = ("kill", "stop", "restart", "inject")


def _parse_target(target: str) -> None:
    if target == "router":
        return
    kind, sep, idx = target.partition(":")
    if kind != "replica" or not sep:
        raise ValueError(
            f"target {target!r}: want 'router', 'replica:N' or "
            "'replica:*'")
    if idx != "*":
        int(idx)  # raises on garbage


@dataclasses.dataclass
class ChaosEvent:
    """One scheduled action against the fleet."""

    offset_s: float
    action: str
    target: str
    duration_s: float = 0.0   # stop: SIGCONT after this long
    restart_s: Optional[float] = None  # kill: relaunch after this long
    spec: str = ""            # inject: ChaosInjector spec string

    def to_dict(self) -> dict:
        d = {"offset_s": round(float(self.offset_s), 6),
             "action": self.action, "target": self.target}
        if self.duration_s:
            d["duration_s"] = round(float(self.duration_s), 6)
        if self.restart_s is not None:
            d["restart_s"] = round(float(self.restart_s), 6)
        if self.spec:
            d["spec"] = self.spec
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosEvent":
        return cls(
            offset_s=float(d["offset_s"]),
            action=str(d["action"]),
            target=str(d["target"]),
            duration_s=float(d.get("duration_s", 0.0)),
            restart_s=(float(d["restart_s"])
                       if d.get("restart_s") is not None else None),
            spec=str(d.get("spec", "")),
        )

    def validate(self, i: int) -> None:
        if self.offset_s < 0:
            raise ValueError(f"event {i}: offset_s must be >= 0")
        if self.action not in _ACTIONS:
            raise ValueError(
                f"event {i}: unknown action {self.action!r} "
                f"(known: {_ACTIONS})")
        _parse_target(self.target)
        if self.action == "stop" and self.duration_s <= 0:
            raise ValueError(
                f"event {i}: stop needs duration_s > 0 (SIGCONT time)")
        if self.action == "inject":
            if self.offset_s != 0:
                raise ValueError(
                    f"event {i}: inject applies at LAUNCH — offset_s "
                    "must be 0 (in-process rules are count-based; the "
                    "schedule cannot reach into a running process)")
            if not self.spec:
                raise ValueError(f"event {i}: inject needs a spec")
            # parse now: a typo'd point must fail at save/load, not
            # silently never fire mid-scenario
            ChaosInjector.from_spec(self.spec)
        if self.action in ("kill", "stop", "restart") \
                and self.target == "router":
            raise ValueError(
                f"event {i}: process actions target replicas (the "
                "router under test must survive to prove recovery); "
                "use an inject rule to fault the router in-process")


@dataclasses.dataclass
class ChaosSchedule:
    """A named, seeded sequence of chaos events."""

    name: str
    events: List[ChaosEvent]
    seed: int = 0
    meta: Dict[str, object] = dataclasses.field(default_factory=dict)

    def validate(self) -> "ChaosSchedule":
        prev = 0.0
        for i, ev in enumerate(self.events):
            ev.validate(i)
            if ev.offset_s < prev:
                raise ValueError(
                    f"event {i}: offsets must be non-decreasing "
                    f"({ev.offset_s} after {prev})")
            prev = ev.offset_s
        return self

    @property
    def duration_s(self) -> float:
        out = 0.0
        for ev in self.events:
            end = ev.offset_s + max(ev.duration_s, ev.restart_s or 0.0)
            out = max(out, end)
        return out

    def launch_injections(self) -> Dict[str, str]:
        """target → combined injector spec for every ``inject`` event
        (applied via ``--chaos`` at process launch)."""
        out: Dict[str, List[str]] = {}
        for ev in self.events:
            if ev.action == "inject":
                out.setdefault(ev.target, []).append(ev.spec)
        return {t: ",".join(specs) for t, specs in out.items()}

    def process_events(self) -> List[ChaosEvent]:
        """The scheduled (non-inject) actions, offset-sorted."""
        return [ev for ev in self.events if ev.action != "inject"]

    # -- persistence ------------------------------------------------------

    def save(self, path: str) -> str:
        self.events.sort(key=lambda ev: ev.offset_s)
        self.validate()
        header = {"kind": SCHEDULE_KIND, "version": SCHEDULE_VERSION,
                  "name": self.name, "seed": int(self.seed),
                  "meta": self.meta, "n_events": len(self.events)}
        with open(path, "w") as fh:
            fh.write(json.dumps(header, sort_keys=True) + "\n")
            for ev in self.events:
                fh.write(json.dumps(ev.to_dict(), sort_keys=True) + "\n")
        return path

    @classmethod
    def load(cls, path: str) -> "ChaosSchedule":
        with open(path) as fh:
            lines = [ln for ln in fh.read().splitlines() if ln.strip()]
        if not lines:
            raise ValueError(f"{path}: empty chaos schedule")
        header = json.loads(lines[0])
        if header.get("kind") != SCHEDULE_KIND:
            raise ValueError(
                f"{path}: not a chaos schedule (kind="
                f"{header.get('kind')!r}; expected {SCHEDULE_KIND!r})")
        if int(header.get("version", -1)) != SCHEDULE_VERSION:
            raise ValueError(
                f"{path}: schedule version {header.get('version')!r} "
                f"not supported (this build reads "
                f"{SCHEDULE_VERSION})")
        sched = cls(name=str(header.get("name", "unnamed")),
                    seed=int(header.get("seed", 0)),
                    meta=dict(header.get("meta") or {}),
                    events=[ChaosEvent.from_dict(json.loads(ln))
                            for ln in lines[1:]])
        return sched.validate()


# -- seeded synthesis ---------------------------------------------------------


def _mix(seed: int, *parts) -> float:
    """Deterministic U[0,1) from (seed, parts) — one draw off the
    shared replay/chaos mixer (``replay/spec.py``
    ``seeded_unit_stream``), so nothing environmental feeds schedule
    timing and the planes' determinism cannot drift apart by copy."""
    from pyspark_tf_gke_tpu.replay.spec import seeded_unit_stream

    return next(seeded_unit_stream(
        ":".join(str(p) for p in (seed,) + parts)))


def synth_chaos(kind: str, *, seed: int = 0, duration_s: float = 10.0,
                replicas: int = 2, name: Optional[str] = None,
                **params) -> ChaosSchedule:
    """Seeded scenario generator — same seed ⇒ identical schedule.

    Kinds:

    * ``kill_one`` — SIGKILL one replica mid-window (jittered around
      the middle), relaunch ``restart_s`` (default duration/4) later:
      THE replica-kill-mid-stream + goodput-recovery scenario.
    * ``hang_one`` — SIGSTOP one replica for ``hang_s`` (default
      duration/4) mid-window: the partition / hung-host shape.
    * ``flaky_probes`` — launch-time router injection failing each
      health probe w.p. ``prob`` (default 0.2): scheduled health
      flapping.
    * ``storm`` — ``n_events`` (default 3) seeded kill/stop events
      spread over the window, round-robin across replicas.
    * ``kill_mid_stream`` — SIGKILL one replica at a PINNED offset
      (``kill_at_s``, default 0.4 × duration — late enough that
      long-generation streams opened at t≈0 are mid-decode), relaunch
      ``restart_s`` later: THE stream-failover scenario. Run it under
      a streaming workload and gate on
      :func:`~pyspark_tf_gke_tpu.chaos.invariants.check_stream_tokens`
      — every client stream must still reach ``[DONE]`` token-exact
      (zero missing, zero duplicated tokens through the router's
      continuation splice).
    * ``kill_mid_scaleup`` — the autopilot's scale-event scenario:
      SIGKILL one of the BOOT replicas at a pinned offset
      (``kill_at_s``, default 0.5 × duration — inside the flash-crowd
      window, i.e. while the autopilot is scaling up), optional
      ``restart_s``. Victim defaults to replica 0 so the kill hits a
      replica that existed before the scale-up (the freshly-started
      one is not in the schedule's index space). Gate on
      exactly-one-terminal (``check_report`` / ``check_traces``).
    * ``kill_prefill_mid_xfer`` — the DISAGGREGATION chaos scenario:
      SIGKILL the prefill replica (``victim``, default 0 — localfleet
      role-split runs put the prefill replica first) at a pinned
      offset (``kill_at_s``, default 0.4 × duration — while long
      prompts are mid prefill-export/KV-handoff), relaunch
      ``restart_s`` (default duration/4) later. Run it under a
      long-prompt workload through a router with
      ``--disagg-min-prompt`` set and gate on exactly-one-terminal
      (``check_report``): every request whose handoff the kill tore
      must land exactly once via the RECOMPUTE fallback on the decode
      pool, and both sides' page-refcount audits must stay green.
    * ``hang_drain`` — the scale-DOWN chaos scenario: SIGSTOP the
      designated drain victim (``victim``, default the highest boot
      index — the autopilot evicts the coldest, which a cold fresh
      replica is) at ``at_s`` (default 0.7 × duration, after a demand
      peak) for ``hang_s`` (default duration/4): the drain the
      autopilot requested hangs instead of exiting, and the do-no-harm
      machinery must neither double-drain nor lose requests.
    """
    events: List[ChaosEvent] = []
    if kind == "kill_mid_stream":
        victim = int(params.pop(
            "victim", int(_mix(seed, "victim") * replicas) % replicas))
        at = float(params.pop("kill_at_s", duration_s * 0.4))
        restart_s = float(params.pop("restart_s", duration_s / 4))
        events.append(ChaosEvent(offset_s=at, action="kill",
                                 target=f"replica:{victim}",
                                 restart_s=restart_s))
    elif kind == "kill_one":
        victim = int(_mix(seed, "victim") * replicas) % replicas
        at = duration_s * (0.35 + 0.3 * _mix(seed, "at"))
        restart_s = float(params.pop("restart_s", duration_s / 4))
        events.append(ChaosEvent(offset_s=at, action="kill",
                                 target=f"replica:{victim}",
                                 restart_s=restart_s))
    elif kind == "hang_one":
        victim = int(_mix(seed, "victim") * replicas) % replicas
        at = duration_s * (0.35 + 0.3 * _mix(seed, "at"))
        hang_s = float(params.pop("hang_s", duration_s / 4))
        events.append(ChaosEvent(offset_s=at, action="stop",
                                 target=f"replica:{victim}",
                                 duration_s=hang_s))
    elif kind == "kill_mid_scaleup":
        victim = int(params.pop("victim", 0)) % replicas
        at = float(params.pop("kill_at_s", duration_s * 0.5))
        restart_s = params.pop("restart_s", None)
        events.append(ChaosEvent(
            offset_s=at, action="kill", target=f"replica:{victim}",
            restart_s=(float(restart_s)
                       if restart_s is not None else None)))
    elif kind == "kill_prefill_mid_xfer":
        victim = int(params.pop("victim", 0)) % replicas
        at = float(params.pop("kill_at_s", duration_s * 0.4))
        restart_s = float(params.pop("restart_s", duration_s / 4))
        events.append(ChaosEvent(offset_s=at, action="kill",
                                 target=f"replica:{victim}",
                                 restart_s=restart_s))
    elif kind == "hang_drain":
        victim = int(params.pop("victim", replicas - 1)) % replicas
        at = float(params.pop("at_s", duration_s * 0.7))
        hang_s = float(params.pop("hang_s", duration_s / 4))
        events.append(ChaosEvent(offset_s=at, action="stop",
                                 target=f"replica:{victim}",
                                 duration_s=hang_s))
    elif kind == "flaky_probes":
        prob = float(params.pop("prob", 0.2))
        events.append(ChaosEvent(
            offset_s=0.0, action="inject", target="router",
            spec=f"seed={seed},router.probe:fail%{prob:g}"))
    elif kind == "storm":
        n = int(params.pop("n_events", 3))
        for i in range(n):
            at = duration_s * (0.15 + 0.7 * _mix(seed, "storm", i))
            victim = i % replicas
            if _mix(seed, "storm_kind", i) < 0.5:
                events.append(ChaosEvent(
                    offset_s=at, action="kill",
                    target=f"replica:{victim}",
                    restart_s=duration_s / 5))
            else:
                events.append(ChaosEvent(
                    offset_s=at, action="stop",
                    target=f"replica:{victim}",
                    duration_s=duration_s / 5))
    else:
        raise ValueError(
            f"unknown chaos kind {kind!r} (known: kill_one, hang_one, "
            "flaky_probes, storm, kill_mid_stream, kill_mid_scaleup, "
            "kill_prefill_mid_xfer, hang_drain)")
    if params:
        raise ValueError(f"unknown synth_chaos params: {sorted(params)}")
    events.sort(key=lambda ev: ev.offset_s)
    return ChaosSchedule(
        name=name or f"{kind}-s{seed}", seed=seed, events=events,
        meta={"kind": kind, "duration_s": duration_s,
              "replicas": replicas,
              **({"streaming": True}
                 if kind == "kill_mid_stream" else {}),
              **({"disagg": True}
                 if kind == "kill_prefill_mid_xfer" else {})}).validate()
