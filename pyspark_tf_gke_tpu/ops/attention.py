"""Attention ops.

The reference has no attention anywhere (its largest model is a 43M-param
CNN — SURVEY §2b), but long-context support is first-class in this
framework, so two implementations live here:

* ``dot_product_attention`` — plain batched attention; XLA fuses it well
  on the MXU for moderate sequence lengths.
* ``ring_attention`` — sequence-parallel attention over the ``sp`` mesh
  axis: each device holds one sequence block of Q/K/V, K/V blocks rotate
  around the ring via ``lax.ppermute`` over ICI, and softmax is
  accumulated online (flash-style running max / normalizer), so the full
  S×S score matrix never materializes and sequence length scales with the
  number of devices. Pattern follows the public ring-attention recipe
  (blockwise attention + ring P2P), re-derived for shard_map.
* ``ulysses_attention`` — the all-to-all alternative (DeepSpeed-Ulysses
  pattern): two ``lax.all_to_all``s swap the sequence sharding for a
  *head* sharding, full attention runs locally on ``H/sp`` heads, and a
  final all-to-all restores sequence sharding. Cheaper than the ring when
  ``sp`` ≤ num_heads and the interconnect does fast all-to-all (ICI);
  the ring wins when S is huge (it never holds the full S per device).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from pyspark_tf_gke_tpu.parallel.compat import shard_map

NEG_INF = -1e30


def dot_product_attention(
    q: jnp.ndarray,  # [B, Sq, H, D]
    k: jnp.ndarray,  # [B, Sk, H, D]
    v: jnp.ndarray,  # [B, Sk, H, D]
    mask: Optional[jnp.ndarray] = None,  # broadcastable to [B, H, Sq, Sk]
    causal: bool = False,
) -> jnp.ndarray:
    """Standard attention in float32 accumulation, bf16-friendly inputs."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        cm = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(cm[None, None], scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    if mask is not None:
        # Rows with no valid key (all-padding queries) output 0, not mean(V).
        valid = jnp.broadcast_to(mask, scores.shape).any(axis=-1)  # [B,H,Sq]
        out = jnp.where(valid.transpose(0, 2, 1)[..., None], out, 0)
    return out


def _ring_block(q, k, v, kv_mask, axis_name: str, axis_size: int, causal: bool):
    """Per-device body: local Q block attends to all K/V blocks as they
    rotate around the ring. Shapes: q [B,Sq,H,D]; k,v [B,Sk,H,D];
    kv_mask [B,Sk] bool or None."""
    scale = q.shape[-1] ** -0.5
    b, sq, h, d = q.shape
    sk = k.shape[1]
    my_index = lax.axis_index(axis_name)

    o = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    m = jnp.full((b, h, sq), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, h, sq), dtype=jnp.float32)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(i, carry):
        o, m, l, k, v, kv_mask = carry
        # Which global block this K/V came from: after i rotations we hold
        # the block originally on device (my_index - i) mod axis_size.
        src = (my_index - i) % axis_size
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = my_index * sq + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
            k_pos = src * sk + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
            s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
        if kv_mask is not None:
            s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
        )
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        if kv_mask is not None:
            kv_mask = lax.ppermute(kv_mask, axis_name, perm)
        return o, m_new, l, k, v, kv_mask

    o, m, l, *_ = lax.fori_loop(0, axis_size, body, (o, m, l, k, v, kv_mask))
    # Rows with no valid key anywhere keep m == NEG_INF (every score was
    # masked); their p/l accumulations are exp(0)=1 garbage — zero them out,
    # matching dot_product_attention's all-padding behavior.
    valid = m > NEG_INF / 2  # [B,H,Sq]
    l = jnp.where(l == 0.0, 1.0, l)
    out = o / l.transpose(0, 2, 1)[..., None]
    out = jnp.where(valid.transpose(0, 2, 1)[..., None], out, 0)
    return out.astype(q.dtype)


def _merge_partial(o, lse, o_i, lse_i):
    """Combine two partial attentions (outputs + logsumexps) over
    disjoint key sets — the flash-style merge. NEG_INF (not -inf) marks
    empty rows, so the -inf-minus--inf NaN case never arises; merged
    garbage rows are 0*w + 0*w = 0."""
    lse_new = jnp.logaddexp(lse, lse_i)
    w = jnp.exp(lse - lse_new)[..., None]
    w_i = jnp.exp(lse_i - lse_new)[..., None]
    return o * w + o_i.astype(jnp.float32) * w_i, lse_new


def _ring_block_flash(q, k, v, kv_mask, axis_name: str, axis_size: int):
    """Ring attention with the Pallas flash kernel as the per-step block
    engine: each ring step runs one fused blockwise attention on the
    resident K/V block (returning out + lse), and partial results merge
    by logsumexp. ``lax.scan`` (not fori_loop) so the ring is
    reverse-mode differentiable; K/V/mask rotate via ppermute inside the
    scan, and their cotangents ride the reversed ring on the way back."""
    from pyspark_tf_gke_tpu.ops.pallas.flash_attention import (
        flash_attention_block,
    )

    b, sq, h, d = q.shape
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    o0 = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    lse0 = jnp.full((b, sq, h), NEG_INF, dtype=jnp.float32)
    have_mask = kv_mask is not None
    mask0 = kv_mask if have_mask else jnp.zeros((), dtype=bool)

    def body(carry, _):
        o, lse, k, v, mask = carry
        o_i, lse_i = flash_attention_block(
            q, k, v, kv_mask=mask if have_mask else None
        )
        o, lse = _merge_partial(o, lse, o_i, lse_i)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        if have_mask:
            mask = lax.ppermute(mask, axis_name, perm)
        return (o, lse, k, v, mask), None

    (o, lse, *_), _ = lax.scan(body, (o0, lse0, k, v, mask0), None,
                               length=axis_size)
    return o.astype(q.dtype)


def _sp_shard_map(body, mesh: Mesh, axis: str, kv_mask):
    """Shared shard_map scaffolding for the sequence-parallel attention
    variants: Q/K/V sharded [data, axis, tp, -] with an optional [data,
    axis] mask (a scalar sentinel stands in when there is none — shard_map
    needs a concrete operand either way)."""
    data_spec = ("dp", "fsdp")
    qkv_spec = P(data_spec, axis, "tp", None)
    mask_spec = P(data_spec, axis) if kv_mask is not None else P()
    if kv_mask is None:
        fn = lambda q, k, v, _: body(q, k, v, None)
        kv_mask_arg = jnp.zeros((), dtype=bool)
    else:
        fn = body
        kv_mask_arg = kv_mask
    wrapped = shard_map(
        fn, mesh=mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec, check_vma=False,
    )
    return lambda q, k, v: wrapped(q, k, v, kv_mask_arg)


def ring_attention(
    q: jnp.ndarray,  # [B, S, H, D] — S sharded over `axis` outside
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    kv_mask: Optional[jnp.ndarray] = None,  # [B, S] bool, S sharded likewise
    axis: str = "sp",
    causal: bool = False,
    use_flash: Optional[bool] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention over mesh axis ``axis``.

    Inputs carry the *global* sequence dimension; shard_map splits it over
    the ring. Batch stays sharded over the data axes, heads over ``tp``.

    ``use_flash`` selects the per-step block engine: the Pallas flash
    kernel with lse-merging (None = auto: TPU backend, per-shard sequence
    >= 512, non-causal — the measured kernel crossover), else the dense
    online-softmax block. Causal ring flash is unsupported (the kernel's
    causal mask is block-local); auto falls back to dense for it.
    """
    axis_size = mesh.shape[axis]
    if axis_size == 1:
        return dot_product_attention(q, k, v,
                                     mask=None if kv_mask is None else kv_mask[:, None, None, :],
                                     causal=causal)
    if use_flash is None:
        from pyspark_tf_gke_tpu.ops.pallas.common import FLASH_MIN_SEQ, on_tpu

        use_flash = (
            not causal and on_tpu()
            and q.shape[1] // axis_size >= FLASH_MIN_SEQ
        )
    if use_flash:
        if causal:
            raise ValueError("ring flash attention does not support causal=True")
        fn = functools.partial(_ring_block_flash, axis_name=axis,
                               axis_size=axis_size)
    else:
        fn = functools.partial(_ring_block, axis_name=axis,
                               axis_size=axis_size, causal=causal)
    return _sp_shard_map(fn, mesh, axis, kv_mask)(q, k, v)


def ulysses_attention(
    q: jnp.ndarray,  # [B, S, H, D] — S sharded over `axis` outside
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    kv_mask: Optional[jnp.ndarray] = None,  # [B, S] bool, S sharded likewise
    axis: str = "sp",
    causal: bool = False,
    use_flash: Optional[bool] = None,
) -> jnp.ndarray:
    """All-to-all sequence parallelism over mesh axis ``axis``.

    Each device starts with a sequence block of all heads; one
    ``all_to_all`` re-shards to all of the sequence for ``H/sp`` heads,
    attention runs locally (exact, not blockwise), and the inverse
    ``all_to_all`` restores the sequence sharding. Head count (after any
    ``tp`` split) must divide by the axis size.

    ``use_flash`` (None = auto: TPU and global seq >= 512) runs the
    local attention through the Pallas flash kernel — the device sees
    the FULL sequence here, so unlike the ring, even ``causal`` works
    (the kernel's positions are global).
    """
    axis_size = mesh.shape[axis]
    if use_flash is None:
        from pyspark_tf_gke_tpu.ops.pallas.common import FLASH_MIN_SEQ, on_tpu

        use_flash = on_tpu() and q.shape[1] >= FLASH_MIN_SEQ
    if axis_size == 1:
        if use_flash:
            from pyspark_tf_gke_tpu.ops.pallas.flash_attention import (
                flash_attention,
            )

            return flash_attention(q, k, v, kv_mask=kv_mask, causal=causal)
        return dot_product_attention(
            q, k, v,
            mask=None if kv_mask is None else kv_mask[:, None, None, :],
            causal=causal,
        )
    from pyspark_tf_gke_tpu.parallel.sharding import mesh_extent_for

    tp = mesh_extent_for("heads", mesh)  # rule-derived, not literal "tp"
    local_heads = q.shape[2] // tp
    if local_heads % axis_size:
        raise ValueError(
            f"ulysses needs per-device head count {local_heads} divisible by "
            f"{axis}={axis_size}; use ring_attention instead"
        )

    def body(q, k, v, mask):
        # [B, S/sp, h, D] -> [B, S, h/sp, D]: split heads, gather sequence.
        q, k, v = (
            lax.all_to_all(t, axis, split_axis=2, concat_axis=1, tiled=True)
            for t in (q, k, v)
        )
        full_mask = (
            None if mask is None
            else lax.all_gather(mask, axis, axis=1, tiled=True)
        )
        if use_flash:
            from pyspark_tf_gke_tpu.ops.pallas.flash_attention import (
                flash_attention,
            )

            out = flash_attention(q, k, v, kv_mask=full_mask, causal=causal)
        else:
            out = dot_product_attention(
                q, k, v,
                mask=None if full_mask is None else full_mask[:, None, None, :],
                causal=causal,
            )
        # [B, S, h/sp, D] -> [B, S/sp, h, D]
        return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)

    return _sp_shard_map(body, mesh, axis, kv_mask)(q, k, v)
