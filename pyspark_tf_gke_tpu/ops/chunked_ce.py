"""Chunked large-vocab softmax cross-entropy.

No counterpart in the reference (its models have no vocabulary head);
this is a TPU-memory optimization for the framework's own LM training
paths. The naive loss materializes fp32 logits ``[B, S, V]`` — at
B=8, S=1024, V=32k that is 1 GiB of HBM *before* the softmax residuals,
and it dwarfs the model itself. This op computes the cross-entropy
directly from the final hidden states and the LM-head weight in vocab
chunks under ``lax.scan``:

* each chunk's logits ``[N, V/C]`` are produced by one MXU matmul and
  folded into an online logsumexp (flash-attention-style running
  max/normalizer), then discarded;
* the scan body is wrapped in ``jax.checkpoint`` so the backward pass
  recomputes chunk logits instead of storing them — peak logits memory
  drops from ``N*V`` to ``N*V/C`` in both passes;
* the label logit and the running argmax (for accuracy metrics) ride
  along in the carry, so callers never need the full logits either.

Numerics: matmul accumulates in float32 (``preferred_element_type``),
reductions are float32 — parity with the dense
``optax.softmax_cross_entropy_with_integer_labels`` path is tested to
tight tolerance (tests/test_chunked_ce.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def chunked_cross_entropy(
    hidden: jnp.ndarray,        # [N, E] activations (any float dtype)
    kernel: jnp.ndarray,        # [E, V] LM-head weight
    bias: Optional[jnp.ndarray],  # [V] or None
    labels: jnp.ndarray,        # [N] int
    num_chunks: int = 8,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token softmax cross-entropy without materializing [N, V].

    Returns ``(loss [N] float32, argmax [N] int32)``.
    """
    n, e = hidden.shape
    v = kernel.shape[1]
    num_chunks = max(1, min(num_chunks, v))
    vc = -(-v // num_chunks)  # ceil
    pad = num_chunks * vc - v

    bias_f = (bias.astype(jnp.float32) if bias is not None
              else jnp.zeros((v,), jnp.float32))
    if pad:
        # Padding columns get zero weight and NEG_INF bias: they
        # contribute exp(NEG_INF)=0 to the normalizer and never win the
        # argmax or match a label.
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
        bias_f = jnp.pad(bias_f, (0, pad), constant_values=NEG_INF)

    # [E, C*Vc] -> [C, E, Vc] chunk stack for the scan.
    k_chunks = kernel.reshape(e, num_chunks, vc).transpose(1, 0, 2)
    b_chunks = bias_f.reshape(num_chunks, vc)
    offsets = jnp.arange(num_chunks, dtype=jnp.int32) * vc
    labels = labels.astype(jnp.int32)

    def body(carry, chunk):
        m, l, lbl_logit, amax_val, amax_idx = carry
        kc, bc, offset = chunk
        logits = jnp.einsum("ne,ev->nv", hidden, kc,
                            preferred_element_type=jnp.float32) + bc

        cm = logits.max(axis=-1)
        new_m = jnp.maximum(m, cm)
        l = l * jnp.exp(m - new_m) + jnp.exp(
            logits - new_m[:, None]).sum(axis=-1)

        local = labels - offset
        in_chunk = (local >= 0) & (local < vc)
        safe = jnp.clip(local, 0, vc - 1)
        gathered = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
        lbl_logit = jnp.where(in_chunk, gathered, lbl_logit)

        cai = logits.argmax(axis=-1).astype(jnp.int32)
        cav = jnp.take_along_axis(logits, cai[:, None], axis=1)[:, 0]
        upd = cav > amax_val
        amax_val = jnp.where(upd, cav, amax_val)
        amax_idx = jnp.where(upd, cai + offset, amax_idx)
        return (new_m, l, lbl_logit, amax_val, amax_idx), None

    init = (
        jnp.full((n,), NEG_INF, jnp.float32),   # running max
        jnp.zeros((n,), jnp.float32),           # running sum-exp
        jnp.full((n,), NEG_INF, jnp.float32),   # label logit
        jnp.full((n,), NEG_INF, jnp.float32),   # argmax value
        jnp.zeros((n,), jnp.int32),             # argmax index
    )
    (m, l, lbl_logit, _, amax_idx), _ = lax.scan(
        jax.checkpoint(body), init, (k_chunks, b_chunks, offsets)
    )
    lse = m + jnp.log(l)
    return lse - lbl_logit, amax_idx
