"""Flash attention forward as a Pallas TPU kernel.

The S×S score matrix never touches HBM: each grid step owns one Q block in
VMEM, loops over K/V blocks with the online-softmax recurrence (running
max ``m``, normalizer ``l``, accumulator in f32), and writes one O block.
Q·Kᵀ and P·V hit the MXU with f32 accumulation.

Layout: inputs are ``[BH, S, D]`` (batch×heads collapsed — each grid row
is independent). Optional additive bias ``[BH, S]`` implements padding
masks (0 for keep, -inf/NEG_INF for drop). ``causal=True`` masks with
block-level skipping (a K block fully in the future is never read).

Backward: ``jax.custom_vjp`` recomputes attention blockwise in plain JAX
(flash-style memory behavior; XLA fuses it well). Residuals are only
(q, k, v, bias) — no S×S tensor is saved.

The public entry ``flash_attention`` takes ``[B, S, H, D]`` like
``ops.attention.dot_product_attention`` and reshapes. Falls back to the
dense path on non-TPU backends unless ``interpret=True`` (used in tests).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-only import; interpret mode works without it
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, block_k: int, causal: bool,
                scale: float):
    # Shapes: q [1, bq, D], k/v [1, S, D], bias [1, S], o [1, bq, D]
    bq = q_ref.shape[1]
    s = k_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)  # Q-block index

    q = q_ref[0].astype(jnp.float32) * scale            # [bq, D]

    m = jnp.full((bq, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((bq, 1), dtype=jnp.float32)
    acc = jnp.zeros((bq, d), dtype=jnp.float32)

    num_kb = s // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                                # [bq, bk]
        scores += bias_ref[0, pl.ds(kb * block_k, block_k)][None, :]
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    if causal:
        # K blocks fully in the future of this Q block are skipped entirely.
        last_kb = jnp.minimum(((qi + 1) * bq + block_k - 1) // block_k, num_kb)
    else:
        last_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m, l, acc))

    valid = m > NEG_INF / 2                              # rows with >=1 unmasked key
    l = jnp.where(l == 0.0, 1.0, l)
    out = jnp.where(valid, acc / l, 0.0)
    o_ref[0] = out.astype(o_ref.dtype)


def _flash_fwd_bh(q, k, v, bias, *, causal: bool, block_q: int, block_k: int,
                  interpret: bool):
    """q,k,v: [BH, S, D]; bias: [BH, S] additive (0 / NEG_INF)."""
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} must be divisible by blocks ({block_q},{block_k})")
    scale = d ** -0.5

    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                               scale=scale)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    grid = (bh, s // block_q)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0), **mem),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0), **mem),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0), **mem),
            pl.BlockSpec((1, s), lambda i, j: (i, 0), **mem),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(q, k, v, bias)


def _reference_bh(q, k, v, bias, causal):
    """Blockwise-free dense reference used for the backward recompute."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    scores += bias[:, None, :]
    if causal:
        s = q.shape[1]
        cm = jnp.tril(jnp.ones((s, s), dtype=bool))
        scores = jnp.where(cm[None], scores, NEG_INF)
    m = scores.max(-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = p.sum(-1, keepdims=True)
    valid = m > NEG_INF / 2
    out = jnp.where(valid, jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32))
                    / jnp.where(l == 0, 1.0, l), 0.0)
    return out.astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_bh(q, k, v, bias, causal, block_q, block_k, interpret):
    return _flash_fwd_bh(q, k, v, bias, causal=causal, block_q=block_q,
                         block_k=block_k, interpret=interpret)


def _flash_bh_fwd(q, k, v, bias, causal, block_q, block_k, interpret):
    out = _flash_fwd_bh(q, k, v, bias, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    return out, (q, k, v, bias)


def _flash_bh_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, bias = residuals
    _, vjp = jax.vjp(lambda q, k, v: _reference_bh(q, k, v, bias, causal), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash_bh.defvjp(_flash_bh_fwd, _flash_bh_bwd)


def flash_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_mask: Optional[jnp.ndarray] = None,  # [B, S] bool
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused attention; drop-in for ``dot_product_attention`` on TPU."""
    b, s, h, d = q.shape
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    if kv_mask is None:
        bias = jnp.zeros((b, s), dtype=jnp.float32)
    else:
        bias = jnp.where(kv_mask.astype(bool), 0.0, NEG_INF).astype(jnp.float32)
    bias = jnp.repeat(bias, h, axis=0)  # [BH, S]

    out = _flash_bh(to_bh(q), to_bh(k), to_bh(v), bias, causal, block_q, block_k,
                    interpret)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
