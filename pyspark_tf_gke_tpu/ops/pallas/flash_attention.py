"""Flash attention forward as a Pallas TPU kernel.

The S×S score matrix never touches HBM: each grid step owns one Q block in
VMEM, loops over K/V blocks with the online-softmax recurrence (running
max ``m``, normalizer ``l``, accumulator in f32), and writes one O block.
Q·Kᵀ and P·V hit the MXU with f32 accumulation.

Layout: inputs are ``[BH, S, D]`` (batch×heads collapsed — each grid row
is independent). Optional additive bias ``[BH, S]`` implements padding
masks (0 for keep, -inf/NEG_INF for drop). ``causal=True`` masks with
block-level skipping (a K block fully in the future is never read).

Backward: ``jax.custom_vjp`` with **Pallas backward kernels** — the
forward additionally emits the per-row logsumexp ``L = m + log(l)``, and
two kernels recompute P blockwise from (q, k, bias, L): one walks K
blocks to produce dQ, the other walks Q blocks to produce dK/dV (the
standard flash-attention backward split). No S×S tensor ever exists in
either pass; residuals are (q, k, v, bias, L, D=rowsum(dO·O)).

The public entry ``flash_attention`` takes ``[B, S, H, D]`` like
``ops.attention.dot_product_attention`` and reshapes. Falls back to the
dense path on non-TPU backends unless ``interpret=True`` (used in tests).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-only import; interpret mode works without it
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, *rest, block_k: int,
                causal: bool, scale: float, use_segs: bool):
    # Shapes: q [1, bq, D], k/v [1, S, D], bias [1, 1, S], o [1, bq, D],
    # lse [1, 1, bq]; with use_segs also segq [1, 1, bq], segk [1, 1, S]
    # (int32 packed-sequence ids — tokens attend within their segment).
    # Row-vectors ride a leading singleton so their last two block dims
    # satisfy Mosaic's (8, 128)-or-full tiling rule.
    if use_segs:
        segq_ref, segk_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    bq = q_ref.shape[1]
    s = k_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)  # Q-block index

    # Matmul operands stay in the input dtype (bf16 hits the MXU at full
    # rate; f32 would run it 8x slower); accumulation and the softmax
    # statistics are f32. The scale is folded into the f32 scores.
    q = q_ref[0]                                         # [bq, D]

    m = jnp.full((bq, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((bq, 1), dtype=jnp.float32)
    acc = jnp.zeros((bq, d), dtype=jnp.float32)

    num_kb = s // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                        # [bq, bk] f32
        scores += bias_ref[0, 0, pl.ds(kb * block_k, block_k)][None, :]
        if use_segs:
            segq = segq_ref[0, 0][:, None]               # [bq, 1]
            segk = segk_ref[0, 0, pl.ds(kb * block_k, block_k)][None, :]
            scores = jnp.where(segq == segk, scores, NEG_INF)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    if causal:
        # K blocks fully in the future of this Q block are skipped entirely.
        last_kb = jnp.minimum(((qi + 1) * bq + block_k - 1) // block_k, num_kb)
    else:
        last_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m, l, acc))

    valid = m > NEG_INF / 2                              # rows with >=1 unmasked key
    l = jnp.where(l == 0.0, 1.0, l)
    out = jnp.where(valid, acc / l, 0.0)
    o_ref[0] = out.astype(o_ref.dtype)
    # Logsumexp residual for the backward kernels; +inf on fully-masked
    # rows makes their recomputed P exactly 0.
    lse_ref[0, 0] = jnp.where(valid, m + jnp.log(l), jnp.inf)[:, 0]


def _flash_fwd_bh(q, k, v, bias, segs=None, *, causal: bool, block_q: int,
                  block_k: int, interpret: bool):
    """q,k,v: [BH, S, D]; bias: [BH, 1, S] additive (0 / NEG_INF);
    segs: optional [BH, 1, S] int32 packed-sequence ids.
    Returns (out [BH, S, D], lse [BH, 1, S])."""
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} must be divisible by blocks ({block_q},{block_k})")
    scale = d ** -0.5

    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal,
                               scale=scale, use_segs=segs is not None)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    grid = (bh, s // block_q)
    qblock = pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j), **mem)
    full_row = pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0), **mem)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0), **mem),
        pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0), **mem),
        pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0), **mem),
        full_row,
    ]
    args = [q, k, v, bias]
    if segs is not None:
        in_specs += [qblock, full_row]   # segq view (q rows), segk view (all keys)
        args += [segs, segs]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0), **mem),
            qblock,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(*args)


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, lse_ref, do_ref, delta_ref,
               *rest, block_k: int, causal: bool, scale: float,
               use_segs: bool):
    # Shapes: q/do/dq [1, bq, D], k/v [1, S, D], bias [1, 1, S],
    # lse/delta [1, 1, bq]. One Q block per grid step, walking K blocks.
    if use_segs:
        segq_ref, segk_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    bq = q_ref.shape[1]
    s = k_ref.shape[1]
    qi = pl.program_id(1)

    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, 0][:, None]                         # [bq, 1]
    delta = delta_ref[0, 0][:, None]                     # [bq, 1]
    acc = jnp.zeros((bq, q_ref.shape[2]), dtype=jnp.float32)

    num_kb = s // block_k

    def body(kb, acc):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        scores += bias_ref[0, 0, pl.ds(kb * block_k, block_k)][None, :]
        if use_segs:
            segq = segq_ref[0, 0][:, None]
            segk = segk_ref[0, 0, pl.ds(kb * block_k, block_k)][None, :]
            scores = jnp.where(segq == segk, scores, NEG_INF)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        p = jnp.exp(scores - lse)                        # exact probs via saved lse
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta) * scale).astype(k_blk.dtype)
        return acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    last_kb = (
        jnp.minimum(((qi + 1) * bq + block_k - 1) // block_k, num_kb)
        if causal else num_kb
    )
    acc = jax.lax.fori_loop(0, last_kb, body, acc)
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, lse_ref, do_ref, delta_ref,
                *rest, block_q: int, causal: bool, scale: float,
                use_segs: bool):
    # Shapes: k/v/dk/dv [1, bk, D], q/do [1, S, D], bias [1, 1, bk],
    # lse/delta [1, 1, S]. One K block per grid step, walking Q blocks.
    if use_segs:
        segq_ref, segk_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    bk = k_ref.shape[1]
    s = q_ref.shape[1]
    ki = pl.program_id(1)

    k_blk = k_ref[0]
    v_blk = v_ref[0]
    bias = bias_ref[0, 0][None, :]                       # [1, bk]
    dk = jnp.zeros(k_blk.shape, dtype=jnp.float32)
    dv = jnp.zeros(v_blk.shape, dtype=jnp.float32)

    num_qb = s // block_q

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
        scores = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale + bias
        if use_segs:
            segq = segq_ref[0, 0, pl.ds(qb * block_q, block_q)][:, None]
            segk = segk_ref[0, 0][None, :]               # [1, bk]
            scores = jnp.where(segq == segk, scores, NEG_INF)
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        p = jnp.exp(scores - lse)                        # [bq, bk] f32
        dv = dv + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        # d(scale·q·kᵀ)/dk = scale·q; fold the scale into ds.
        ds = (p * (dp - delta) * scale).astype(q_blk.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    # Causal: Q blocks strictly before this K block never attend to it.
    first_qb = (ki * bk) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(first_qb, num_qb, body, (dk, dv))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_bh(q, k, v, bias, lse, out, do, segs=None, *, causal, block_q,
                  block_k, interpret, delta_shift=None):
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    scale = d ** -0.5
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = delta[:, None, :]                            # [BH, 1, S]
    if delta_shift is not None:
        # lse cotangent from _flash_bh_lse: ds = p*(dp - delta + g_lse).
        delta = delta - delta_shift.astype(jnp.float32)
    use_segs = segs is not None

    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    full = lambda last: pl.BlockSpec((1, s, last), lambda i, j: (i, 0, 0), **mem)
    full_row = pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0), **mem)
    qrow = pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j), **mem)
    krow = pl.BlockSpec((1, 1, block_k), lambda i, j: (i, 0, j), **mem)

    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0), **mem),
        full(d), full(d), full_row, qrow,
        pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0), **mem),
        qrow,
    ]
    dq_args = [q, k, v, bias, lse, do, delta]
    if use_segs:
        dq_specs += [qrow, full_row]
        dq_args += [segs, segs]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale, use_segs=use_segs),
        grid=(bh, s // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(*dq_args)

    dkv_specs = [
        full(d),
        pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0), **mem),
        pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0), **mem),
        krow, full_row, full(d), full_row,
    ]
    dkv_args = [q, k, v, bias, lse, do, delta]
    if use_segs:
        dkv_specs += [full_row, krow]
        dkv_args += [segs, segs]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale, use_segs=use_segs),
        grid=(bh, s // block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0), **mem),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_bh(q, k, v, bias, segs, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_bh(q, k, v, bias, segs, causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret)
    return out


def _flash_bh_fwd(q, k, v, bias, segs, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_bh(q, k, v, bias, segs, causal=causal,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret)
    return out, (q, k, v, bias, segs, lse, out)


def _flash_bh_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, bias, segs, lse, out = residuals
    dq, dk, dv = _flash_bwd_bh(q, k, v, bias, lse, out, g, segs, causal=causal,
                               block_q=block_q, block_k=block_k,
                               interpret=interpret)
    return dq, dk, dv, None, None


_flash_bh.defvjp(_flash_bh_fwd, _flash_bh_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_bh_lse(q, k, v, bias, segs, causal, block_q, block_k, interpret):
    """Flash attention that also returns the per-row logsumexp — the
    building block for cross-device merging (ring attention combines
    per-ring-step partial outputs by their lse)."""
    return _flash_fwd_bh(q, k, v, bias, segs, causal=causal, block_q=block_q,
                         block_k=block_k, interpret=interpret)


def _flash_bh_lse_fwd(q, k, v, bias, segs, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_bh(q, k, v, bias, segs, causal=causal,
                             block_q=block_q, block_k=block_k,
                             interpret=interpret)
    return (out, lse), (q, k, v, bias, segs, lse, out)


def _flash_bh_lse_bwd(causal, block_q, block_k, interpret, residuals, gs):
    """dlse/dscores is exactly the softmax probs, so the lse cotangent
    folds into the delta term the kernels already subtract:
    ds = p*(dp - delta + g_lse) — pass (delta - g_lse) and the unchanged
    backward kernels produce the combined gradient."""
    g_out, g_lse = gs
    q, k, v, bias, segs, lse, out = residuals
    dq, dk, dv = _flash_bwd_bh(q, k, v, bias, lse, out, g_out, segs,
                               causal=causal, block_q=block_q,
                               block_k=block_k, interpret=interpret,
                               delta_shift=g_lse)
    return dq, dk, dv, None, None


_flash_bh_lse.defvjp(_flash_bh_lse_fwd, _flash_bh_lse_bwd)


def _pick_seq_block(s: int, desired: int) -> int:
    """Largest Mosaic-valid sequence block: the [.., 1, S] row-vectors
    make S a lane dim, so blocks must be multiples of 128 (or full S)."""
    from pyspark_tf_gke_tpu.ops.pallas.common import pick_block

    return pick_block(s, desired, 128)


def _prep_bh(q, k, v, kv_mask, segment_ids, block_q, block_k, interpret):
    b, s, h, d = q.shape
    if interpret is None:
        from pyspark_tf_gke_tpu.ops.pallas.common import on_tpu

        interpret = not on_tpu()
    if block_q is None:
        block_q = _pick_seq_block(s, DEFAULT_BLOCK_Q)
    if block_k is None:
        block_k = _pick_seq_block(s, DEFAULT_BLOCK_K)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    if kv_mask is None:
        bias = jnp.zeros((b, s), dtype=jnp.float32)
    else:
        bias = jnp.where(kv_mask.astype(bool), 0.0, NEG_INF).astype(jnp.float32)
    bias = jnp.repeat(bias, h, axis=0)[:, None, :]  # [BH, 1, S]
    segs = None
    if segment_ids is not None:
        segs = jnp.repeat(segment_ids.astype(jnp.int32), h, axis=0)[:, None, :]
    return to_bh(q), to_bh(k), to_bh(v), bias, segs, block_q, block_k, interpret


def flash_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_mask: Optional[jnp.ndarray] = None,  # [B, S] bool
    causal: bool = False,
    segment_ids: Optional[jnp.ndarray] = None,  # [B, S] int — packed sequences
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused attention; drop-in for ``dot_product_attention`` on TPU.
    ``segment_ids`` confines attention within matching ids (packed
    sequences / block-diagonal masking), composable with ``kv_mask``
    and ``causal``."""
    b, s, h, d = q.shape
    qb, kb, vb, bias, segs, block_q, block_k, interpret = _prep_bh(
        q, k, v, kv_mask, segment_ids, block_q, block_k, interpret
    )
    out = _flash_bh(qb, kb, vb, bias, segs, causal, block_q, block_k, interpret)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def flash_attention_block(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_mask: Optional[jnp.ndarray] = None,  # [B, S] bool
    segment_ids: Optional[jnp.ndarray] = None,  # [B, S] int
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """One attention *block*: returns ``(out [B,S,H,D], lse [B,S,H])``
    so a caller can combine partial attentions over K/V blocks held
    elsewhere (ring attention merges per-ring-step results by lse).
    Rows with no unmasked key get lse = NEG_INF (no mass) and out = 0 —
    finite, so the logsumexp merge stays NaN-free."""
    b, s, h, d = q.shape
    qb, kb, vb, bias, segs, block_q, block_k, interpret = _prep_bh(
        q, k, v, kv_mask, segment_ids, block_q, block_k, interpret
    )
    out, lse = _flash_bh_lse(qb, kb, vb, bias, segs, False, block_q, block_k,
                             interpret)
    out = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    lse = lse[:, 0, :].reshape(b, h, s).transpose(0, 2, 1)  # [B, S, H]
    lse = jnp.where(jnp.isposinf(lse), NEG_INF, lse)
    return out, lse
