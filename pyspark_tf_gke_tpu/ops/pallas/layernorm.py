"""Fused LayerNorm as a Pallas TPU kernel.

One VMEM-resident pass per row block: mean, variance (rsqrt), scale+shift
— a single kernel instead of the half-dozen HBM round-trips a naive
implementation costs. f32 statistics regardless of input dtype.

An optional **residual input** is summed inside the kernel
(``y = LN(x + r)``): transformer blocks are exactly this pattern, and
keeping the add inside recovers the add+LN fusion XLA would otherwise do
itself — without it the opaque kernel boundary costs one extra HBM pass
and the Pallas LN loses to plain XLA in-graph.

Backward via custom_vjp with the standard closed-form LN gradient
(plain JAX; XLA fuses it into two passes).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_ROWS = 256


def _ln_kernel(x_ref, scale_ref, bias_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)                       # [rows, D]
    mean = x.mean(axis=-1, keepdims=True)
    xc = x - mean
    var = (xc * xc).mean(axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = xc * inv * scale_ref[:].astype(jnp.float32)[None, :] + \
        bias_ref[:].astype(jnp.float32)[None, :]
    o_ref[:] = y.astype(o_ref.dtype)


def _ln_add_kernel(x_ref, r_ref, scale_ref, bias_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32) + r_ref[:].astype(jnp.float32)
    mean = x.mean(axis=-1, keepdims=True)
    xc = x - mean
    var = (xc * xc).mean(axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = xc * inv * scale_ref[:].astype(jnp.float32)[None, :] + \
        bias_ref[:].astype(jnp.float32)[None, :]
    o_ref[:] = y.astype(o_ref.dtype)


def _ln_forward(x2, scale, bias, eps, block_rows, interpret, r2=None):
    n, d = x2.shape
    block_rows = min(block_rows, n)
    if n % block_rows:
        raise ValueError(f"rows {n} not divisible by block_rows {block_rows}")
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    row_spec = pl.BlockSpec((block_rows, d), lambda i: (i, 0), **mem)
    vec_spec = pl.BlockSpec((d,), lambda i: (0,), **mem)
    if r2 is None:
        kernel, in_specs, args = (
            functools.partial(_ln_kernel, eps=eps),
            [row_spec, vec_spec, vec_spec],
            (x2, scale, bias),
        )
    else:
        kernel, in_specs, args = (
            functools.partial(_ln_add_kernel, eps=eps),
            [row_spec, row_spec, vec_spec, vec_spec],
            (x2, r2, scale, bias),
        )
    return pl.pallas_call(
        kernel,
        grid=(n // block_rows,),
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((n, d), x2.dtype),
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln(x2, scale, bias, eps, block_rows, interpret):
    return _ln_forward(x2, scale, bias, eps, block_rows, interpret)


def _ln_fwd(x2, scale, bias, eps, block_rows, interpret):
    return _ln_forward(x2, scale, bias, eps, block_rows, interpret), (x2, scale)


def _ln_bwd(eps, block_rows, interpret, residuals, g):
    x2, scale = residuals
    x = x2.astype(jnp.float32)
    g = g.astype(jnp.float32)
    d = x.shape[-1]
    mean = x.mean(-1, keepdims=True)
    xc = x - mean
    var = (xc * xc).mean(-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = xc * inv
    gs = g * scale.astype(jnp.float32)[None, :]
    dx = inv / d * (d * gs - gs.sum(-1, keepdims=True) - xhat * (gs * xhat).sum(-1, keepdims=True))
    dscale = (g * xhat).sum(0)
    dbias = g.sum(0)
    return dx.astype(x2.dtype), dscale.astype(scale.dtype), dbias.astype(scale.dtype)


_ln.defvjp(_ln_fwd, _ln_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ln_res(x2, r2, scale, bias, eps, block_rows, interpret):
    return _ln_forward(x2, scale, bias, eps, block_rows, interpret, r2=r2)


def _ln_res_fwd(x2, r2, scale, bias, eps, block_rows, interpret):
    out = _ln_forward(x2, scale, bias, eps, block_rows, interpret, r2=r2)
    return out, (x2, r2, scale)


def _ln_res_bwd(eps, block_rows, interpret, residuals, g):
    x2, r2, scale = residuals
    # d(x+r) flows identically to both inputs; reuse the closed-form LN
    # gradient on the recomputed sum (XLA fuses the add into the bwd).
    xsum = (x2.astype(jnp.float32) + r2.astype(jnp.float32)).astype(x2.dtype)
    dx, dscale, dbias = _ln_bwd(eps, block_rows, interpret, (xsum, scale), g)
    return dx, dx.astype(r2.dtype), dscale, dbias


_ln_res.defvjp(_ln_res_fwd, _ln_res_bwd)


def _pick_block(n: int, block_rows: int) -> int:
    from pyspark_tf_gke_tpu.ops.pallas.common import pick_block

    return pick_block(n, block_rows, 8)


def fused_layernorm(
    x: jnp.ndarray,                  # [..., D]
    scale: jnp.ndarray,              # [D]
    bias: jnp.ndarray,               # [D]
    eps: float = 1e-6,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    interpret: Optional[bool] = None,
    residual: Optional[jnp.ndarray] = None,  # same shape as x; y = LN(x+r)
) -> jnp.ndarray:
    if interpret is None:
        from pyspark_tf_gke_tpu.ops.pallas.common import on_tpu

        interpret = not on_tpu()
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    br = _pick_block(x2.shape[0], block_rows)
    if residual is None:
        return _ln(x2, scale, bias, eps, br, interpret).reshape(shape)
    r2 = residual.reshape(-1, shape[-1])
    return _ln_res(x2, r2, scale, bias, eps, br, interpret).reshape(shape)
