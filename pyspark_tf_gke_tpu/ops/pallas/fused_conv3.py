"""Fused 3x3 conv (stride 1, SAME) with BN-stat epilogue + on-read norm.

Completes the conv+BN fusion family started in ``fused_matmul.py``:
with only the 1x1 convs fused, each bottleneck block still pays one
materialized normalized tensor (norm1's output feeding the XLA 3x3
conv) and one statistics reduction read (norm2's stats over the 3x3
output). Owning the 3x3 conv removes both: the kernel reads the RAW
conv1 output, applies norm1's ``relu(x*a+b)`` per tile in VMEM, runs
the nine tap matmuls from a zero-padded VMEM scratch (SAME padding:
the pad ring is zero AFTER normalize+relu, matching XLA's semantics of
padding the normalized input), and writes the raw output together with
its per-channel sum/sumsq partials.

Grid is ``(B,)`` — one image per step; every ResNet-50 stage's full
H x W x C activation fits VMEM comfortably (largest: 56x56x64 bf16 =
400 KB). The nine taps are static slices of the padded scratch, so no
halo exchange or dynamic indexing is needed. Backward reuses the same
shapes: ``dxn`` is the flipped-tap convolution of ``dy`` (same padded-
scratch trick), masked and scaled in-epilogue with the ``d a``/``d b``
reductions; ``dw`` accumulates the nine ``win^T @ dy`` products across
the batch grid — the output block's index map is constant, so the
accumulator stays VMEM-resident for the whole (consecutive) grid and
cross-step accumulation is well-defined.

Stride-2 blocks keep the XLA conv (3 of 16 blocks in ResNet-50): the
strided halo bookkeeping isn't worth kernel complexity for <20% of the
3x3 FLOPs. ``models/resnet.py::FusedBottleneckBlock`` picks per-block.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from pyspark_tf_gke_tpu.ops.pallas.fused_matmul import (
    _mem, _resolve_interpret)


def _transform(x, a_ref, b_ref, transform: bool, relu: bool):
    if not transform:
        return x
    # a/b arrive as (1, K) blocks (Mosaic's 1-D operand layout check
    # rejects partial 1-D tiles on real TPUs — see fused_matmul.py);
    # [None] lifts them to (1, 1, K) to broadcast over (H, W, K).
    t = x.astype(jnp.float32) * a_ref[...][None] + b_ref[...][None]
    if relu:
        t = jnp.maximum(t, 0.0)
    return t.astype(x.dtype)


def _fwd_kernel(x_ref, w_ref, a_ref, b_ref, y_ref, s_ref, pad_ref, *,
                transform: bool, relu: bool, want_stats: bool):
    h, w_, k = x_ref.shape[1], x_ref.shape[2], x_ref.shape[3]
    n = w_ref.shape[3]
    xn = _transform(x_ref[0], a_ref, b_ref, transform, relu)
    pad_ref[...] = jnp.zeros_like(pad_ref)
    pad_ref[1:h + 1, 1:w_ + 1, :] = xn
    acc = jnp.zeros((h * w_, n), jnp.float32)
    for dh in range(3):
        for dw in range(3):
            win = pad_ref[dh:dh + h, dw:dw + w_, :].reshape(h * w_, k)
            acc += jax.lax.dot_general(
                win, w_ref[dh, dw], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    y_ref[0] = acc.reshape(h, w_, n).astype(y_ref.dtype)
    if want_stats:
        yr = acc.astype(y_ref.dtype).astype(jnp.float32)
        s_ref[0] = jnp.stack([yr.sum(axis=0), (yr * yr).sum(axis=0)])


def _fwd_call(x, w, a, b, *, relu, want_stats, interpret):
    bsz, h, w_, k = x.shape
    n = w.shape[3]
    transform = a is not None
    if not transform:
        a = jnp.ones((k,), jnp.float32)
        b = jnp.zeros((k,), jnp.float32)
    mem = _mem()
    kernel = functools.partial(_fwd_kernel, transform=transform, relu=relu,
                               want_stats=want_stats)
    y, stats = pl.pallas_call(
        kernel,
        grid=(bsz,),
        in_specs=[
            pl.BlockSpec((1, h, w_, k), lambda i: (i, 0, 0, 0), **mem),
            pl.BlockSpec((3, 3, k, n), lambda i: (0, 0, 0, 0), **mem),
            pl.BlockSpec((1, k), lambda i: (0, 0), **mem),
            pl.BlockSpec((1, k), lambda i: (0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, h, w_, n), lambda i: (i, 0, 0, 0), **mem),
            pl.BlockSpec((1, 2, n), lambda i: (i, 0, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bsz, h, w_, n), x.dtype),
            jax.ShapeDtypeStruct((bsz, 2, n), jnp.float32),
        ],
        scratch_shapes=[_pad_scratch(h, w_, k, x.dtype)],
        interpret=interpret,
    )(x, w, a.reshape(1, k), b.reshape(1, k))
    return y, stats.sum(axis=0)


def _pad_scratch(h, w_, k, dtype):
    from pyspark_tf_gke_tpu.ops.pallas.fused_matmul import pltpu

    if pltpu is None:  # pragma: no cover
        raise RuntimeError("fused_conv3 needs pallas TPU scratch support")
    return pltpu.VMEM((h + 2, w_ + 2, k), dtype)


def _dx_kernel(dy_ref, w_ref, x_ref, a_ref, b_ref, dx_ref, ds_ref, pad_ref,
               *, transform: bool, relu: bool):
    h, w_, n = dy_ref.shape[1], dy_ref.shape[2], dy_ref.shape[3]
    k = w_ref.shape[2]
    pad_ref[...] = jnp.zeros_like(pad_ref)
    pad_ref[1:h + 1, 1:w_ + 1, :] = dy_ref[0]
    u = jnp.zeros((h * w_, k), jnp.float32)
    for dh in range(3):
        for dw in range(3):
            # transposed conv: tap (dh, dw) of the forward gathers
            # x[p + (dh-1, dw-1)] into y[p]; its adjoint scatters
            # dy[p - (dh-1, dw-1)] into dx[p] — i.e. the FLIPPED tap
            # window over padded dy
            win = pad_ref[2 - dh:2 - dh + h,
                          2 - dw:2 - dw + w_, :].reshape(h * w_, n)
            u += jax.lax.dot_general(
                win, w_ref[dh, dw], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
    if transform:
        xf = x_ref[0].astype(jnp.float32).reshape(h * w_, k)
        a = a_ref[...]  # (1, k): broadcasts over rows
        if relu:
            t = xf * a + b_ref[...]
            u = jnp.where(t > 0.0, u, 0.0)
        dx_ref[0] = (u * a).reshape(h, w_, k).astype(dx_ref.dtype)
        ds_ref[0] = jnp.stack([(u * xf).sum(axis=0), u.sum(axis=0)])
    else:
        dx_ref[0] = u.reshape(h, w_, k).astype(dx_ref.dtype)


def _dx_call(dy, w, x, a, b, *, relu, interpret):
    bsz, h, w_, n = dy.shape
    k = w.shape[2]
    transform = a is not None
    if not transform:
        a = jnp.ones((k,), jnp.float32)
        b = jnp.zeros((k,), jnp.float32)
    mem = _mem()
    kernel = functools.partial(_dx_kernel, transform=transform, relu=relu)
    dx, dstats = pl.pallas_call(
        kernel,
        grid=(bsz,),
        in_specs=[
            pl.BlockSpec((1, h, w_, n), lambda i: (i, 0, 0, 0), **mem),
            pl.BlockSpec((3, 3, k, n), lambda i: (0, 0, 0, 0), **mem),
            pl.BlockSpec((1, h, w_, k), lambda i: (i, 0, 0, 0), **mem),
            pl.BlockSpec((1, k), lambda i: (0, 0), **mem),
            pl.BlockSpec((1, k), lambda i: (0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, h, w_, k), lambda i: (i, 0, 0, 0), **mem),
            pl.BlockSpec((1, 2, k), lambda i: (i, 0, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bsz, h, w_, k), x.dtype),
            jax.ShapeDtypeStruct((bsz, 2, k), jnp.float32),
        ],
        scratch_shapes=[_pad_scratch(h, w_, n, dy.dtype)],
        interpret=interpret,
    )(dy, w, x, a.reshape(1, k), b.reshape(1, k))
    return dx, dstats.sum(axis=0)


def _dw_kernel(x_ref, dy_ref, a_ref, b_ref, dw_ref, pad_ref, *,
               transform: bool, relu: bool):
    i = pl.program_id(0)
    h, w_, k = x_ref.shape[1], x_ref.shape[2], x_ref.shape[3]
    n = dy_ref.shape[3]
    xn = _transform(x_ref[0], a_ref, b_ref, transform, relu)
    pad_ref[...] = jnp.zeros_like(pad_ref)
    pad_ref[1:h + 1, 1:w_ + 1, :] = xn
    dy = dy_ref[0].reshape(h * w_, n)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    for dh in range(3):
        for dw in range(3):
            win = pad_ref[dh:dh + h, dw:dw + w_, :].reshape(h * w_, k)
            dw_ref[dh, dw] += jax.lax.dot_general(
                win, dy, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)


def _dw_call(x, dy, a, b, *, relu, interpret):
    bsz, h, w_, k = x.shape
    n = dy.shape[3]
    transform = a is not None
    if not transform:
        a = jnp.ones((k,), jnp.float32)
        b = jnp.zeros((k,), jnp.float32)
    mem = _mem()
    kernel = functools.partial(_dw_kernel, transform=transform, relu=relu)
    # out index map is CONSTANT over the (only) grid dim, so the f32
    # accumulator block stays resident across consecutive steps — the
    # safe accumulation pattern (cf. fused_matmul's no-revisit rule)
    dw = pl.pallas_call(
        kernel,
        grid=(bsz,),
        in_specs=[
            pl.BlockSpec((1, h, w_, k), lambda i: (i, 0, 0, 0), **mem),
            pl.BlockSpec((1, h, w_, n), lambda i: (i, 0, 0, 0), **mem),
            pl.BlockSpec((1, k), lambda i: (0, 0), **mem),
            pl.BlockSpec((1, k), lambda i: (0, 0), **mem),
        ],
        out_specs=pl.BlockSpec((3, 3, k, n), lambda i: (0, 0, 0, 0), **mem),
        out_shape=jax.ShapeDtypeStruct((3, 3, k, n), jnp.float32),
        scratch_shapes=[_pad_scratch(h, w_, k, x.dtype)],
        interpret=interpret,
    )(x, dy, a.reshape(1, k), b.reshape(1, k))
    return dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _conv3(x, w, a, b, relu, want_stats, interpret):
    y, stats = _fwd_call(x, w, a, b, relu=relu, want_stats=want_stats,
                         interpret=interpret)
    return (y, stats[0], stats[1]) if want_stats else y


def _conv3_fwd(x, w, a, b, relu, want_stats, interpret):
    out = _conv3(x, w, a, b, relu, want_stats, interpret)
    y = out[0] if want_stats else out
    return out, (x, w, a, b, y)


def _conv3_bwd(relu, want_stats, interpret, res, g):
    x, w, a, b, y = res
    if want_stats:
        gy, gs, gss = g
        dy = (gy.astype(jnp.float32) + gs[None, None, None, :]
              + 2.0 * y.astype(jnp.float32) * gss[None, None, None, :]
              ).astype(y.dtype)
    else:
        dy = g
    transform = a is not None
    dx, dstats = _dx_call(dy, w, x, a, b, relu=relu, interpret=interpret)
    dw = _dw_call(x, dy, a, b, relu=relu, interpret=interpret).astype(w.dtype)
    if transform:
        return dx, dw, dstats[0].astype(a.dtype), dstats[1].astype(b.dtype)
    return dx, dw, None, None


_conv3.defvjp(_conv3_fwd, _conv3_bwd)


def conv3_norm_stats(
    x: jnp.ndarray,               # [B, H, W, K] RAW producer output
    w: jnp.ndarray,               # [3, 3, K, N]
    a: Optional[jnp.ndarray] = None,   # [K] f32 folded norm scale
    b: Optional[jnp.ndarray] = None,   # [K] f32 folded norm shift
    *,
    relu: bool = True,
    want_stats: bool = False,
    interpret: Optional[bool] = None,
):
    """Stride-1 SAME 3x3 conv of ``relu(x*a+b)`` (transform optional)
    with optional per-output-channel (sum, sumsq) epilogue."""
    if (a is None) != (b is None):
        raise ValueError("a and b must be provided together")
    if w.shape[:2] != (3, 3):
        raise ValueError(f"3x3 kernel expected, got {w.shape}")
    return _conv3(x, w, a, b, relu if a is not None else False,
                  want_stats, _resolve_interpret(interpret))
