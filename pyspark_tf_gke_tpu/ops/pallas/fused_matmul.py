"""Fused 1x1-conv (matmul) kernels with BN-stat epilogues for ResNet.

Why this exists (the round-4 MFU investigation, docs/PARITY.md): on the
v5e, ResNet-50's normalization costs 8.2 ms/step = 29% of the step while
the conv-only floor is 38.6% MFU. The probe pinned the cost on *pass
structure*, not the batch reduction: every BatchNorm between a conv and
its consumer is an unfused HBM read-modify-write of a full activation
tensor (GroupNorm — no batch reduction at all — measured the same), and
a standalone norm kernel cannot beat XLA's own fused elementwise passes.
The only way to remove the passes is to move the norm work inside the
convs' own HBM touches. A bottleneck block's 1x1 convs ARE matmuls
(NHWC: (B*H*W, Cin) @ (Cin, Cout)), so this file implements a Pallas
matmul with:

- **input transform**: ``relu((x - mean) * inv * scale + bias)`` applied
  per K-channel on tiles already in VMEM, so a consumer conv reads the
  producer's RAW output and normalizes for free (the separate
  normalize write + read disappears);
- **stats epilogue**: per-output-channel ``sum`` / ``sum-of-squares``
  accumulated while the f32 accumulator tile is still in registers, so
  the next norm's statistics cost no extra read of the conv output.

The input transform is folded to per-channel affine form
``relu(x * a + b)`` with ``a = scale * rsqrt(var + eps)`` and
``b = bias - mean * a`` — host-side f32 vector math, free.

Backward rides the same two kernel shapes (``dx = dy @ w^T`` with the
relu mask and ``d a/d b`` reductions fused into the epilogue;
``dw = xn^T @ dy`` re-applying the input transform on the fly), wrapped
in ``jax.custom_vjp`` at *kernel* granularity: the surrounding
statistics math (mean/var from sums, the ``a``/``b`` folding) is plain
JAX, so BatchNorm's gradient-through-statistics chain is handled by
autodiff, not hand-derived.

Stats are computed on the bf16-rounded output values (not the raw f32
accumulator): the consumer normalizes the bf16 tensor it reads, so the
statistics must describe exactly that tensor — this matches what a
separate XLA reduction over the stored output would compute.

Reference counterpart: none — the reference's largest model is a plain
CNN (``/root/reference/workloads/raw-tf/train_tf_ps.py:346-378``) and
its BatchNorm story is whatever Keras emits. This kernel family exists
to hit the TPU roofline the reference never approached.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - exercised only on TPU images
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_M = 448   # divides B*H*W for every ResNet-50 stage at B=64k
DEFAULT_BLOCK_N = 512
DEFAULT_BLOCK_K = 512


def _pick(n: int, desired: int, multiple: int) -> int:
    from pyspark_tf_gke_tpu.ops.pallas.common import pick_block

    return pick_block(n, desired, multiple)


def _mem(spec_kwargs=None):
    return {} if _VMEM is None else {"memory_space": _VMEM}


def _scratch(shape):
    if pltpu is None:  # pragma: no cover - env without pallas TPU support
        raise RuntimeError(
            "fused_matmul needs jax.experimental.pallas.tpu for VMEM "
            "scratch accumulators; unavailable in this environment")
    return pltpu.VMEM(shape, jnp.float32)


# ---------------------------------------------------------------------------
# forward kernel: y = xn @ w (+ stats), xn = relu(x*a + b) or raw x
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, w_ref, a_ref, b_ref, y_ref, s_ref, acc_ref, *,
                nk: int, transform: bool, relu: bool, want_stats: bool):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    if transform:
        # a/b ride as (1, bk) 2-D blocks: Mosaic rejects 1-D operand
        # blocks that don't match XLA's 1-D layout tile (seen on real
        # v5e: "XLA layout {0:T(1024)} does not match Mosaic layout
        # {0:T(512)} for f32[1024]"), while (1, K) lanes-shaped vectors
        # follow the ordinary 2-D tiling rules.
        t = x.astype(jnp.float32) * a_ref[...] + b_ref[...]
        if relu:
            t = jnp.maximum(t, 0.0)
        xn = t.astype(x.dtype)  # bf16 feed matches the unfused norm's dtype
    else:
        xn = x
    acc_ref[...] += jax.lax.dot_general(
        xn, w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _emit():
        acc = acc_ref[...]
        y_ref[...] = acc.astype(y_ref.dtype)
        if want_stats:
            # Per-M-tile PARTIAL stats over the ROUNDED values the
            # consumer will read. Each (i, j) writes its own partial —
            # no cross-iteration output-window accumulation, which is
            # undefined for non-consecutive revisits on real TPUs (the
            # i dim is outermost). The caller reduces the tiny
            # (m_tiles, 2, N) f32 array in one XLA pass.
            yr = acc.astype(y_ref.dtype).astype(jnp.float32)
            s_ref[...] = jnp.stack(
                [yr.sum(axis=0), (yr * yr).sum(axis=0)])[None]


def _fwd_call(x, w, a, b, *, relu, want_stats, block_m, block_n, block_k,
              interpret):
    m, kdim = x.shape
    _, n = w.shape
    bm = _pick(m, block_m, 8)
    bn = _pick(n, block_n, 128)
    bk = _pick(kdim, block_k, 128)
    nk = kdim // bk
    transform = a is not None
    if not transform:  # placeholder operands keep one kernel signature
        a = jnp.ones((kdim,), jnp.float32)
        b = jnp.zeros((kdim,), jnp.float32)
    mem = _mem()
    kernel = functools.partial(
        _fwd_kernel, nk=nk, transform=transform, relu=relu,
        want_stats=want_stats)
    y, stats = pl.pallas_call(
        kernel,
        grid=(m // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k), **mem),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j), **mem),
            pl.BlockSpec((1, bk), lambda i, j, k: (0, k), **mem),
            pl.BlockSpec((1, bk), lambda i, j, k: (0, k), **mem),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda i, j, k: (i, j), **mem),
            pl.BlockSpec((1, 2, bn), lambda i, j, k: (i, 0, j), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), x.dtype),
            jax.ShapeDtypeStruct((m // bm, 2, n), jnp.float32),
        ],
        scratch_shapes=[_scratch((bm, bn))],
        interpret=interpret,
    )(x, w, a.reshape(1, kdim), b.reshape(1, kdim))
    # reduce the per-M-tile partials: (m_tiles, 2, n) f32 — a few MB at
    # most, one cheap XLA pass, no undefined revisit semantics
    return y, stats.sum(axis=0)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _dx_kernel(dy_ref, w_ref, x_ref, a_ref, b_ref, dx_ref, ds_ref, acc_ref,
               *, nn_: int, transform: bool, relu: bool):
    n = pl.program_id(2)

    @pl.when(n == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        dy_ref[...], w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(n == nn_ - 1)
    def _emit():
        u = acc_ref[...]  # d xn
        if transform:
            xf = x_ref[...].astype(jnp.float32)
            a = a_ref[...]  # (1, bk): broadcasts over rows
            if relu:
                t = xf * a + b_ref[...]
                u = jnp.where(t > 0.0, u, 0.0)  # relu mask on d t
            dx_ref[...] = (u * a).astype(dx_ref.dtype)
            # per-M-tile partials for (da, db) — same no-revisit rule as
            # the forward stats epilogue; caller sums over M tiles
            ds_ref[...] = jnp.stack(
                [(u * xf).sum(axis=0), u.sum(axis=0)])[None]
        else:
            dx_ref[...] = u.astype(dx_ref.dtype)


def _dx_call(dy, w, x, a, b, *, relu, block_m, block_n, block_k, interpret):
    m, n = dy.shape
    kdim = w.shape[0]
    bm = _pick(m, block_m, 8)
    bk = _pick(kdim, block_k, 128)
    bn = _pick(n, block_n, 128)
    nn_ = n // bn
    transform = a is not None
    if not transform:
        a = jnp.ones((kdim,), jnp.float32)
        b = jnp.zeros((kdim,), jnp.float32)
    mem = _mem()
    kernel = functools.partial(_dx_kernel, nn_=nn_, transform=transform,
                               relu=relu)
    dx, dstats = pl.pallas_call(
        kernel,
        grid=(m // bm, kdim // bk, nn_),
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, j, n: (i, n), **mem),
            pl.BlockSpec((bk, bn), lambda i, j, n: (j, n), **mem),
            pl.BlockSpec((bm, bk), lambda i, j, n: (i, j), **mem),
            pl.BlockSpec((1, bk), lambda i, j, n: (0, j), **mem),
            pl.BlockSpec((1, bk), lambda i, j, n: (0, j), **mem),
        ],
        out_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, n: (i, j), **mem),
            pl.BlockSpec((1, 2, bk), lambda i, j, n: (i, 0, j), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, kdim), x.dtype),
            jax.ShapeDtypeStruct((m // bm, 2, kdim), jnp.float32),
        ],
        scratch_shapes=[_scratch((bm, bk))],
        interpret=interpret,
    )(dy, w, x, a.reshape(1, kdim), b.reshape(1, kdim))
    return dx, dstats.sum(axis=0)


def _dw_kernel(x_ref, dy_ref, a_ref, b_ref, dw_ref, acc_ref, *,
               nm: int, transform: bool, relu: bool):
    mstep = pl.program_id(2)

    @pl.when(mstep == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    if transform:
        t = x.astype(jnp.float32) * a_ref[...] + b_ref[...]
        if relu:
            t = jnp.maximum(t, 0.0)
        xn = t.astype(x.dtype)
    else:
        xn = x
    acc_ref[...] += jax.lax.dot_general(
        xn, dy_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(mstep == nm - 1)
    def _emit():
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


def _dw_call(x, dy, a, b, *, relu, block_m, block_n, block_k, interpret):
    m, kdim = x.shape
    _, n = dy.shape
    bm = _pick(m, block_m, 8)
    bk = _pick(kdim, block_k, 128)
    bn = _pick(n, block_n, 128)
    nm = m // bm
    transform = a is not None
    if not transform:
        a = jnp.ones((kdim,), jnp.float32)
        b = jnp.zeros((kdim,), jnp.float32)
    mem = _mem()
    kernel = functools.partial(_dw_kernel, nm=nm, transform=transform,
                               relu=relu)
    return pl.pallas_call(
        kernel,
        grid=(kdim // bk, n // bn, nm),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, mstep: (mstep, i), **mem),
            pl.BlockSpec((bm, bn), lambda i, j, mstep: (mstep, j), **mem),
            pl.BlockSpec((1, bk), lambda i, j, mstep: (0, i), **mem),
            pl.BlockSpec((1, bk), lambda i, j, mstep: (0, i), **mem),
        ],
        out_specs=pl.BlockSpec((bk, bn), lambda i, j, mstep: (i, j), **mem),
        out_shape=jax.ShapeDtypeStruct((kdim, n), dy.dtype),
        scratch_shapes=[_scratch((bk, bn))],
        interpret=interpret,
    )(x, dy, a.reshape(1, kdim), b.reshape(1, kdim))


# ---------------------------------------------------------------------------
# custom-vjp ops
# ---------------------------------------------------------------------------


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    if interpret is None:
        from pyspark_tf_gke_tpu.ops.pallas.common import on_tpu

        return not on_tpu()
    return interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _nrm_mm(x, w, a, b, relu, want_stats, interpret):
    y, stats = _fwd_call(
        x, w, a, b, relu=relu, want_stats=want_stats,
        block_m=DEFAULT_BLOCK_M, block_n=DEFAULT_BLOCK_N,
        block_k=DEFAULT_BLOCK_K, interpret=interpret)
    return (y, stats[0], stats[1]) if want_stats else y


def _nrm_mm_fwd(x, w, a, b, relu, want_stats, interpret):
    out = _nrm_mm(x, w, a, b, relu, want_stats, interpret)
    y = out[0] if want_stats else out
    return out, (x, w, a, b, y)


def _nrm_mm_bwd(relu, want_stats, interpret, res, g):
    x, w, a, b, y = res
    if want_stats:
        gy, gs, gss = g
        # cotangent through the stat outputs: d sum -> +gs per column,
        # d sumsq -> +2*y*gss. One fused XLA elementwise pass.
        dy = (gy.astype(jnp.float32) + gs[None, :]
              + 2.0 * y.astype(jnp.float32) * gss[None, :]).astype(y.dtype)
    else:
        dy = g
    transform = a is not None
    dx, dstats = _dx_call(
        dy, w, x, a, b, relu=relu, block_m=DEFAULT_BLOCK_M,
        block_n=DEFAULT_BLOCK_N, block_k=DEFAULT_BLOCK_K,
        interpret=interpret)
    dw = _dw_call(
        x, dy, a, b, relu=relu, block_m=DEFAULT_BLOCK_M,
        block_n=DEFAULT_BLOCK_N, block_k=DEFAULT_BLOCK_K,
        interpret=interpret).astype(w.dtype)
    if transform:
        return dx, dw, dstats[0].astype(a.dtype), dstats[1].astype(b.dtype)
    return dx, dw, None, None


_nrm_mm.defvjp(_nrm_mm_fwd, _nrm_mm_bwd)


def norm_relu_matmul(
    x: jnp.ndarray,              # [M, K] RAW producer output (pre-norm)
    w: jnp.ndarray,              # [K, N]
    a: Optional[jnp.ndarray] = None,   # [K] f32: scale * rsqrt(var+eps)
    b: Optional[jnp.ndarray] = None,   # [K] f32: bias - mean * a
    *,
    relu: bool = True,
    want_stats: bool = False,
    interpret: Optional[bool] = None,
):
    """``relu(x*a + b) @ w`` with optional per-output-channel stats.

    With ``a``/``b`` None the transform is skipped (plain matmul +
    stats epilogue). Returns ``y`` or ``(y, sum, sumsq)`` where
    ``sum``/``sumsq`` are f32 per-column reductions of the rounded
    output — exactly what BatchNorm statistics need, for free.
    """
    if (a is None) != (b is None):
        raise ValueError("a and b must be provided together")
    return _nrm_mm(x, w, a, b, relu if a is not None else False,
                   want_stats, _resolve_interpret(interpret))


def bn_fold(mean: jnp.ndarray, var: jnp.ndarray, scale: jnp.ndarray,
            bias: jnp.ndarray, eps: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold BN parameters+statistics to the per-channel affine
    ``(a, b)`` the kernels consume: ``norm(x) = x*a + b``."""
    a = scale.astype(jnp.float32) * jax.lax.rsqrt(
        var.astype(jnp.float32) + eps)
    b = bias.astype(jnp.float32) - mean.astype(jnp.float32) * a
    return a, b


def stats_to_moments(s: jnp.ndarray, ss: jnp.ndarray,
                     count: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum, sumsq, N) -> (mean, biased variance) — flax BatchNorm's
    biased-variance convention (``mean(x^2) - mean(x)^2``)."""
    mean = s / count
    var = jnp.maximum(ss / count - mean * mean, 0.0)
    return mean, var
