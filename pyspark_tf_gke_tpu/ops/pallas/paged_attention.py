"""Paged-attention decode kernel: ragged block-table reads over a
global KV page pool (the TPU analog of vLLM's PagedAttention, Kwon et
al., SOSP'23).

The continuous-batching engine (``train/continuous.py``) stores K/V in
a single page pool per layer — ``k_pages [N, P, H_kv, D]`` — and each
slot owns an int32 row of a block table ``[num_slots, max_pages]``
naming its pages in order. Decode attention for slot ``i`` must read
only the pages that hold its ``fills[i]`` live tokens; everything else
in the pool belongs to other requests.

Kernel layout (``pltpu.PrefetchScalarGridSpec``): grid ``(slot,
page)``; the block table and fill levels ride as scalar-prefetch
operands so the K/V page ``BlockSpec`` index maps can *gather through
the table* — block ``(i, j)`` fetches pool page ``block_table[i, j]``.
Ragged early-stop: for ``j`` past the slot's last live page the index
map CLAMPS to that last live page — Mosaic's pipeline skips the DMA
when the block index repeats, so HBM traffic is proportional to each
slot's *filled* tokens, not ``max_pages`` — and ``pl.when`` skips the
compute. Online softmax (running max / normalizer / f32 accumulator in
VMEM scratch, carried across the sequential page grid dim) produces
the output at the last page step, exactly the flash-attention
recurrence over table-gathered blocks.

int8 KV rides along: when the pool is int8, per-(position, head) f32
scale pages are gathered through the same table and the dequant
(convert * scale) happens in-kernel on the VMEM-resident page.

``paged_attention_reference`` is the pure-JAX oracle (gather + masked
dot, the same math as the dense slot-decode path in
``models/causal_lm.py``): the non-TPU fallback and the numerics
reference the interpret-mode kernel is tested against, mirroring
``flash_attention.py``'s ``interpret=`` pattern so CPU CI exercises
the identical code path.

Multi-query chunks (``paged_attention_chunk``): chunked prefill writes
a prompt piece of ``S`` tokens straight into a slot's pages and then
needs attention FOR those S queries over the slot's prior pages plus
the piece itself — the same block-table gather with an in-chunk causal
mask (query ``i`` at absolute position ``fill - S + i`` sees keys at
positions ``<= fill - S + i``). The single-query decode kernel is the
``S = 1`` instance of the same program; both share one kernel body, so
the sweep in ``tools/smoke_check.py --kernels-only`` covers both.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only import; interpret mode works without it
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def paged_attention_chunk_reference(
    q: jnp.ndarray,            # [B, S, H, D] chunk of query tokens
    k_pages: jnp.ndarray,      # [N, P, H_kv, D] (dtype or int8)
    v_pages: jnp.ndarray,      # [N, P, H_kv, D]
    block_table: jnp.ndarray,  # [B, max_pages] int32; >= N = unallocated
    fills: jnp.ndarray,        # [B] int32 live tokens INCLUDING the chunk
    k_scales: Optional[jnp.ndarray] = None,  # [N, P, H_kv] f32 (int8 pool)
    v_scales: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Pure-JAX oracle for the multi-query chunk: gather every table
    page densely, mask causally per query (query ``i`` sits at absolute
    position ``fills - S + i`` and sees keys at positions ``<= fills -
    S + i``), softmax in f32 — mathematically identical to the dense
    slot-decode chunk attention in ``models/causal_lm.py`` (masked
    scores contribute exactly 0 mass). The chunk's own K/V must already
    be IN the pages (the caller writes before attending — in-chunk
    causality then falls out of the same position mask). Query rows
    with no valid key (``fills - S + i < 0``, incl. ``fills <= 0``
    empty slots) return zeros. Sentinel (out-of-range) table entries
    are clamped; whatever page they read is masked."""
    n, p_sz, hkv, d = k_pages.shape
    b, s, h, _ = q.shape
    mp = block_table.shape[1]
    g = h // hkv
    safe = jnp.minimum(block_table, n - 1)
    k = k_pages[safe].reshape(b, mp * p_sz, hkv, d)
    v = v_pages[safe].reshape(b, mp * p_sz, hkv, d)
    if k_scales is not None:
        ks = k_scales[safe].reshape(b, mp * p_sz, hkv)
        vs = v_scales[safe].reshape(b, mp * p_sz, hkv)
        k = (k.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
        v = (v.astype(jnp.float32) * vs[..., None]).astype(q.dtype)
    q5 = q.reshape(b, s, hkv, g, d)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", q5, k,
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    q_abs = fills[:, None] - s + jnp.arange(s)[None, :]          # [B, S]
    valid = (jnp.arange(mp * p_sz)[None, None, :]
             <= q_abs[:, :, None])                               # [B, S, K]
    scores = jnp.where(valid[:, None, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v).reshape(b, s, h, d)
    return jnp.where(q_abs[:, :, None, None] >= 0, out, 0).astype(q.dtype)


def paged_attention_reference(
    q: jnp.ndarray,            # [B, H, D]
    k_pages: jnp.ndarray,      # [N, P, H_kv, D] (dtype or int8)
    v_pages: jnp.ndarray,      # [N, P, H_kv, D]
    block_table: jnp.ndarray,  # [B, max_pages] int32; >= N = unallocated
    fills: jnp.ndarray,        # [B] int32 live tokens per slot
    k_scales: Optional[jnp.ndarray] = None,  # [N, P, H_kv] f32 (int8 pool)
    v_scales: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Single-query decode oracle: the ``S = 1`` case of the chunk
    reference (query at position ``fill - 1`` masks ``k_pos < fill``).
    Rows with ``fills <= 0`` return zeros."""
    return paged_attention_chunk_reference(
        q[:, None], k_pages, v_pages, block_table, fills,
        k_scales=k_scales, v_scales=v_scales)[:, 0]


def _paged_kernel(bt_ref, fills_ref, q_ref, kp_ref, vp_ref, *rest,
                  page_size: int, hkv: int, scale: float, quant: bool,
                  s_q: int):
    # Shapes: q [1, S, H, D] (S = s_q query tokens — 1 on the decode
    # path); kp/vp [1, P, Hkv, D] (the table-gathered page); with quant
    # also ks/vs [1, P, Hkv] f32; o [1, S, H, D]; scratch m/l
    # [S*H, 1] f32, acc [S*H, D] f32, rows laid out kv-head-major:
    # row = hk * (S * G) + s * G + g.
    if quant:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    fill = fills_ref[i]
    live_pages = (fill + page_size - 1) // page_size  # ceil

    @pl.when(j < live_pages)
    def _accumulate():
        q = q_ref[0]                                 # [S, H, D]
        s, h, d = q.shape
        g = h // hkv
        k = kp_ref[0]                                # [P, Hkv, D]
        v = vp_ref[0]
        if quant:
            k = (k.astype(jnp.float32) * ks_ref[0][..., None]).astype(q.dtype)
            v = (v.astype(jnp.float32) * vs_ref[0][..., None]).astype(q.dtype)
        # Per-KV-head 2D dots (Mosaic wants plain matmuls): each cached
        # KV head is read ONCE for its whole query group x chunk — the
        # GQA bandwidth win survives paging and chunking alike.
        rows = []
        for hk in range(hkv):
            rows.append(jax.lax.dot_general(
                q[:, hk * g:(hk + 1) * g].reshape(s * g, d), k[:, hk, :],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))
        scores = jnp.concatenate(rows, axis=0) * scale   # [S*H, P] f32
        # Causal mask per query row: row r holds query s_idx = (r mod
        # S*G) // G at absolute position fill - S + s_idx; it sees keys
        # at positions <= that. S = 1 degenerates to k_pos < fill (the
        # decode mask).
        k_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (s * h, page_size), 1)
        r = jax.lax.broadcasted_iota(jnp.int32, (s * h, page_size), 0)
        q_abs = fill - s + (r % (s * g)) // g
        scores = jnp.where(k_pos <= q_abs, scores, NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, scores.max(axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_prev - m_new)
        outs = []
        for hk in range(hkv):
            outs.append(jax.lax.dot_general(
                p[hk * (s * g):(hk + 1) * (s * g)].astype(v.dtype),
                v[:, hk, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.concatenate(outs, axis=0)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        m = m_ref[:]
        l = l_ref[:]
        valid = m > NEG_INF / 2      # query rows with >= 1 live key
        l = jnp.where(l == 0.0, 1.0, l)
        out = jnp.where(valid, acc_ref[:] / l, 0.0)      # [S*H, D]
        s, h, d = o_ref.shape[1:]
        g = h // hkv
        if s_q == 1:
            # kv-head-major row layout IS head order when S = 1 — keep
            # the decode path free of the transpose below
            o_ref[0] = out.reshape(1, h, d).astype(o_ref.dtype)
        else:
            out = out.reshape(hkv, s, g, d).transpose(1, 0, 2, 3)
            o_ref[0] = out.reshape(s, h, d).astype(o_ref.dtype)


def _paged_pallas(q, k_pages, v_pages, block_table, fills, k_scales,
                  v_scales, interpret: bool):
    # q arrives [B, S, H, D]; S is static (one compiled program per
    # chunk width — the engine uses exactly one width plus S=1 decode).
    n, p_sz, hkv, d = k_pages.shape
    b, s_q, h, _ = q.shape
    mp = block_table.shape[1]
    quant = k_scales is not None

    def page_map(i, j, bt, f):
        # Clamp dead iterations to the slot's LAST LIVE page: a
        # repeated block index skips the DMA, so pages past the fill
        # level are never re-fetched (ragged bandwidth). Sentinel
        # (unallocated) entries clamp into the pool; their compute is
        # pl.when-skipped anyway.
        last = jnp.maximum((f[i] - 1) // p_sz, 0)
        page = bt[i, jnp.minimum(j, last)]
        return jnp.minimum(page, n - 1), 0, 0, 0

    q_spec = pl.BlockSpec((1, s_q, h, d), lambda i, j, bt, f: (i, 0, 0, 0))
    page_spec = pl.BlockSpec((1, p_sz, hkv, d), page_map)
    in_specs = [q_spec, page_spec, page_spec]
    args = [q, k_pages, v_pages]
    if quant:
        def scale_map(i, j, bt, f):
            return page_map(i, j, bt, f)[:3]

        scale_spec = pl.BlockSpec((1, p_sz, hkv), scale_map)
        in_specs += [scale_spec, scale_spec]
        args += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, s_q, h, d),
                               lambda i, j, bt, f: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((s_q * h, 1), jnp.float32),
            pltpu.VMEM((s_q * h, 1), jnp.float32),
            pltpu.VMEM((s_q * h, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, page_size=p_sz, hkv=hkv,
                               scale=d ** -0.5, quant=quant, s_q=s_q)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s_q, h, d), q.dtype),
        interpret=interpret,
    )(block_table.astype(jnp.int32), fills.astype(jnp.int32), *args)


def paged_attention(
    q: jnp.ndarray,            # [B, H, D] one decode token per slot
    k_pages: jnp.ndarray,      # [N, P, H_kv, D]
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,  # [B, max_pages] int32
    fills: jnp.ndarray,        # [B] int32 (valid tokens incl. the one
    #                            just written; 0 = empty slot -> zeros)
    k_scales: Optional[jnp.ndarray] = None,
    v_scales: Optional[jnp.ndarray] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Decode attention through a block table over a KV page pool.
    Returns ``[B, H, D]``. On non-TPU backends (``interpret=None``)
    falls back to the pure-JAX reference — the same dispatch contract
    as ``flash_attention``; ``interpret=True`` forces the kernel in
    interpret mode (tests / numerics oracle)."""
    return paged_attention_chunk(
        q[:, None], k_pages, v_pages, block_table, fills,
        k_scales=k_scales, v_scales=v_scales, interpret=interpret)[:, 0]


def paged_attention_chunk(
    q: jnp.ndarray,            # [B, S, H, D] chunk of query tokens
    k_pages: jnp.ndarray,      # [N, P, H_kv, D]
    v_pages: jnp.ndarray,
    block_table: jnp.ndarray,  # [B, max_pages] int32
    fills: jnp.ndarray,        # [B] int32 live tokens INCLUDING the
    #                            chunk's S (query i sits at fill-S+i;
    #                            0 = empty slot -> zeros)
    k_scales: Optional[jnp.ndarray] = None,
    v_scales: Optional[jnp.ndarray] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Multi-query chunk attention through a block table (chunked
    prefill: the chunk's K/V are already in the pages; each query masks
    causally at its own absolute position). Returns ``[B, S, H, D]``.
    ``S`` is static — one compiled program per chunk width. Dispatch
    contract matches :func:`paged_attention`."""
    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be passed together")
    h, hkv = q.shape[2], k_pages.shape[2]
    if h % hkv:
        raise ValueError(f"num_kv_heads {hkv} must divide num_heads {h}")
    if interpret is None:
        from pyspark_tf_gke_tpu.ops.pallas.common import on_tpu

        if pltpu is None or not on_tpu():
            return paged_attention_chunk_reference(
                q, k_pages, v_pages, block_table, fills,
                k_scales=k_scales, v_scales=v_scales)
        interpret = False
    return _paged_pallas(q, k_pages, v_pages, block_table, fills,
                         k_scales, v_scales, interpret)
