"""Shared helpers for the Pallas TPU kernels."""

from __future__ import annotations


def pick_block(n: int, desired: int, multiple: int) -> int:
    """Largest divisor of ``n`` <= ``desired`` that is a multiple of
    ``multiple`` (Mosaic tiling: 8 for sublane/row blocks, 128 for lane
    blocks), else the whole axis as one block."""
    for blk in range(min(desired, n), multiple - 1, -1):
        if n % blk == 0 and blk % multiple == 0:
            return blk
    return n
