"""Shared helpers for the Pallas TPU kernels."""

from __future__ import annotations

import jax

# Auto-flash threshold (measured on v5e, fwd+bwd per train step): below
# this sequence length XLA's fused dense attention wins (kernel dispatch
# and unfusable reshapes dominate); at/above it the Pallas kernel wins —
# 1.2x at S=1024, 2.3x at S=4096, 6x at S=8192 (where dense hits the
# S^2-materialization memory cliff). Shared by the model dispatch
# (models/bert.py resolve_use_flash), ring and Ulysses attention.
FLASH_MIN_SEQ = 512


def on_tpu() -> bool:
    """True when the active backend compiles Pallas TPU kernels."""
    return jax.default_backend() in ("tpu", "axon")


def pick_block(n: int, desired: int, multiple: int) -> int:
    """Largest divisor of ``n`` <= ``desired`` that is a multiple of
    ``multiple`` (Mosaic tiling: 8 for sublane/row blocks, 128 for lane
    blocks), else the whole axis as one block."""
    for blk in range(min(desired, n), multiple - 1, -1):
        if n % blk == 0 and blk % multiple == 0:
            return blk
    return n
