from pyspark_tf_gke_tpu.ops.pallas.flash_attention import flash_attention
from pyspark_tf_gke_tpu.ops.pallas.layernorm import fused_layernorm

__all__ = ["flash_attention", "fused_layernorm"]
