from pyspark_tf_gke_tpu.ops.pallas.flash_attention import flash_attention
from pyspark_tf_gke_tpu.ops.pallas.layernorm import fused_layernorm
from pyspark_tf_gke_tpu.ops.pallas.paged_attention import (
    paged_attention,
    paged_attention_reference,
)

__all__ = ["flash_attention", "fused_layernorm", "paged_attention",
           "paged_attention_reference"]
