from pyspark_tf_gke_tpu.ops.attention import (
    dot_product_attention,
    ring_attention,
    ulysses_attention,
)

__all__ = ["dot_product_attention", "ring_attention", "ulysses_attention"]
