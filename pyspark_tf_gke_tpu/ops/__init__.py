from pyspark_tf_gke_tpu.ops.attention import (
    dot_product_attention,
    ring_attention,
    ulysses_attention,
)
from pyspark_tf_gke_tpu.ops.chunked_ce import chunked_cross_entropy

__all__ = [
    "dot_product_attention",
    "ring_attention",
    "ulysses_attention",
    "chunked_cross_entropy",
]
