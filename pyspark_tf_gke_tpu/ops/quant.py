"""Weight-only int8 quantization for serving.

No counterpart in the reference (it serves nothing — its terminal
artifact is a saved Keras model, SURVEY §5); this is a TPU-first
optimization for the framework's own decode path: single-token decoding
is HBM-bound on *weight* reads (every step streams every matmul weight
for one token of compute), so storing weights as int8 + per-channel
scales cuts that traffic 4× vs the float32 params flax keeps at rest
(2× vs a bf16 cast). Dequantization happens inside the jitted step —
XLA fuses the convert+scale into the matmul operand, so the wide
weights never round-trip through HBM.

Mechanics: symmetric per-output-channel quantization of 2-D kernels
(``q = round(w / s)``, ``s = max|w| / 127`` per column). ``QTensor`` is
a registered pytree node, so a quantized param tree flows through
``jax.jit`` / ``device_put`` / flax ``apply`` plumbing unchanged;
``dequantize_tree`` (called inside the jit) restores a dense pytree.

LayerNorm scales and biases stay un-quantized (1-D params are cheap);
embedding tables — 2-D and large — ARE quantized for their storage
footprint, and the decode path dequantizes them ONCE per generate call
outside the scan (``dequantize_embeddings``): lookups gather single
rows, so streaming the whole table through an in-loop barrier would
cost far more than it saves.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """int8 weight + per-output-channel float32 scale."""

    q: jnp.ndarray      # int8, same shape as the original kernel
    scale: jnp.ndarray  # float32; (shape[-1],) for per-column kernels,
    #                     (rows, 1) for per-row embedding tables
    dtype: Any          # original dtype, restored on dequantize

    def tree_flatten(self):
        return (self.q, self.scale), (self.dtype,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self) -> jnp.ndarray:
        return (self.q.astype(jnp.float32) * self.scale).astype(self.dtype)


def is_embedding_path(path) -> bool:
    """True when a pytree key path addresses an ``nn.Embed`` table
    (param name ``embedding``) — the single definition shared by
    quantize-time granularity choice, decode-time hoisting, and bundle
    restore, so the three can't silently diverge."""
    return any(getattr(k, "key", None) == "embedding" for k in path)


def quantize_tensor(w: jnp.ndarray, axis: int = -1) -> QTensor:
    """Symmetric per-channel int8 quantization. ``axis`` is the channel
    axis that keeps one scale per slice (reduced over all others):
    ``-1`` = per-output-column (dense kernels), ``0`` = per-row
    (embedding tables — each gathered row quantized independently, so a
    single outlier row cannot coarsen every other token's embedding)."""
    wf = jnp.asarray(w, jnp.float32)
    axis = axis % wf.ndim
    reduce_axes = tuple(a for a in range(wf.ndim) if a != axis)
    amax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    # keep the historical flat (C,) shape for the last-axis case; per-row
    # scales stay keepdims-shaped so dequantize broadcasts over columns
    if axis == wf.ndim - 1:
        scale = scale.reshape(-1)
    return QTensor(q, scale, jnp.asarray(w).dtype)


def quantize_tree(params, min_size: int = 4096):
    """Quantize every 2-D kernel with >= min_size elements, which for
    the transformer stack means the dense kernels AND the embedding
    tables; embedding rows are gathered, not streamed, so quantizing
    them costs nothing at decode and saves checkpoint/HBM bytes too.
    Dense kernels get per-output-column scales (the matmul-operand
    granularity); ``nn.Embed`` tables (param name ``embedding``) get
    per-row scales — a per-column scale there would be computed over the
    entire vocabulary, letting one outlier row coarsen every token."""

    def maybe_q(path, leaf):
        arr = jnp.asarray(leaf)
        if arr.ndim == 2 and arr.size >= min_size and jnp.issubdtype(
                arr.dtype, jnp.floating):
            return quantize_tensor(
                arr, axis=0 if is_embedding_path(path) else -1)
        return leaf

    return jax.tree_util.tree_map_with_path(maybe_q, params)


def dequantize_tree(params):
    """Inverse of quantize_tree; call INSIDE the jit so XLA fuses the
    convert+scale into each matmul and bf16 weights never hit HBM."""
    return jax.tree.map(
        lambda l: l.dequantize() if isinstance(l, QTensor) else l,
        params, is_leaf=lambda l: isinstance(l, QTensor))


def dequantize_embeddings(params):
    """Dequantize only the QTensor leaves that are ``nn.Embed`` tables
    (param name ``embedding``). Decode gathers single rows from these,
    so they should dequant once OUTSIDE the scan (hoisted, loop-
    invariant) rather than stream through the in-loop barrier with the
    matmul weights."""

    def fix(path, leaf):
        if isinstance(leaf, QTensor) and is_embedding_path(path):
            return leaf.dequantize()
        return leaf

    # tree_map_with_path (not a dict walk) so FrozenDict and any other
    # mapping container get the same treatment.
    return jax.tree_util.tree_map_with_path(
        fix, params, is_leaf=lambda l: isinstance(l, QTensor))


def inloop_dequantize(params):
    """Dequantize QTensor leaves INSIDE a decode loop body, each behind
    an ``optimization_barrier`` so XLA cannot hoist the wide weights out
    of the loop — every step streams int8 from HBM and the convert+scale
    fuses into the matmuls. Dense leaves (incl. pre-dequantized
    embeddings) pass through un-barriered. Shared by ``generate`` and
    ``beam_search``."""

    def deq(leaf):
        if isinstance(leaf, QTensor):
            q, s = jax.lax.optimization_barrier((leaf.q, leaf.scale))
            return QTensor(q, s, leaf.dtype).dequantize()
        return leaf

    return jax.tree.map(deq, params, is_leaf=lambda l: isinstance(l, QTensor))


def is_quantized(params) -> bool:
    return any(isinstance(l, QTensor) for l in jax.tree.leaves(
        params, is_leaf=lambda l: isinstance(l, QTensor)))


def quantization_error(w, qt: QTensor) -> float:
    """Max abs error of the roundtrip, for tests/diagnostics."""
    return float(jnp.max(jnp.abs(jnp.asarray(w, jnp.float32) -
                                 qt.dequantize().astype(jnp.float32))))


def tree_bytes(params) -> int:
    """On-device bytes of a (possibly quantized) param tree."""
    total = 0
    for leaf in jax.tree.leaves(params,
                                is_leaf=lambda l: isinstance(l, QTensor)):
        if isinstance(leaf, QTensor):
            total += leaf.q.size * 1 + leaf.scale.size * 4
        else:
            arr = jnp.asarray(leaf)
            total += arr.size * arr.dtype.itemsize
    return total
