"""CSV data path with the exact semantics of the reference's loader
(``workloads/raw-tf/train_tf_ps.py:53-149``): loss parity depends on
matching its row-skip rules and label-vocabulary ordering bit-for-bit
(SURVEY §7 "hard parts").

Semantics preserved:

* a row is dropped when the label column is missing/empty, when any
  numeric feature is missing/empty/"nan" (case-insensitive), or when any
  field fails to parse;
* the label vocabulary is ``sorted(set(labels))`` — deterministic
  alphabetical order;
* features come back float32, label indices int32.
"""

from __future__ import annotations

import csv
import io
from typing import List, Optional, Tuple
from urllib.request import urlopen

import numpy as np


def open_text(path_or_url: str) -> io.TextIOBase:
    """Open a local file, an HTTP(S) URL (reference:
    ``train_tf_ps.py:53-73``), or a ``gs://`` object (the reference's
    cloud data path, ``spark_workload_to_cloud_k8s.py:40-48``) as text."""
    from pyspark_tf_gke_tpu.utils.fs import fs_open, is_remote

    if path_or_url.startswith("http://") or path_or_url.startswith("https://"):
        return io.TextIOWrapper(urlopen(path_or_url), encoding="utf-8")
    if is_remote(path_or_url):
        return io.TextIOWrapper(fs_open(path_or_url, "rb"), encoding="utf-8")
    return open(path_or_url, "r", encoding="utf-8")


def load_csv(
    source: str,
    numeric_features: Optional[List[str]] = None,
    label_col: str = "subpopulation",
) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Parse a CSV into (features float32, label indices int32, sorted vocab)."""
    if numeric_features is None:
        numeric_features = ["value", "lower_ci", "upper_ci"]

    features: List[List[float]] = []
    labels_raw: List[str] = []

    with open_text(source) as fh:
        reader = csv.DictReader(fh)
        for row in reader:
            try:
                label = row.get(label_col, "").strip()
                if not label:
                    continue
                feats = []
                ok = True
                for col in numeric_features:
                    value = row.get(col, "").strip()
                    if value == "" or value.lower() == "nan":
                        ok = False
                        break
                    feats.append(float(value))
                if not ok:
                    continue
                features.append(feats)
                labels_raw.append(label)
            except Exception:
                continue  # skip malformed rows

    if not features:
        raise RuntimeError("No valid rows were parsed from the dataset.")

    vocab = sorted(set(labels_raw))
    index_map = {s: i for i, s in enumerate(vocab)}
    y_idx = np.array([index_map[s] for s in labels_raw], dtype=np.int32)
    return np.asarray(features, dtype=np.float32), y_idx, vocab
