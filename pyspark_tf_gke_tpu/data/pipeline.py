"""Host-side batching and device placement.

Replaces the reference's ``tf.data`` pipeline + per-worker
``InputContext.shard`` pattern (``train_tf_ps.py:312-313, 596-601``) with
the SPMD equivalents:

* ``train_validation_split`` — the reference's deterministic seeded split
  (``np.random.default_rng(seed)`` shuffle, tail = validation;
  ``train_tf_ps.py:281-294, 655-661``), shared by CSV and image paths;
* ``host_shard`` — each *process* keeps rows ``i ≡ process_index (mod
  process_count)`` (the ``dataset.shard(num_input_pipelines, id)``
  analog);
* ``BatchIterator`` — per-epoch reshuffle + fixed-size batches;
* ``put_global_batch`` — assembles per-host local batches into one global
  jax.Array with a ``NamedSharding`` over the data axes
  (``jax.make_array_from_process_local_data``), so the jitted step sees a
  single logical batch regardless of host count.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding

from pyspark_tf_gke_tpu.utils.seeding import DEFAULT_SEED, np_rng


def train_validation_split(
    n: int,
    validation_split: float,
    seed: int = DEFAULT_SEED,
) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic (train_idx, val_idx): seeded shuffle, last
    ``n*validation_split`` (clamped to 1..n-1) rows become validation —
    bit-identical to the reference split."""
    idx = np.arange(n)
    rng = np_rng(seed)
    rng.shuffle(idx)
    if not validation_split:
        return idx, np.array([], dtype=np.int64)
    val_size = int(n * float(validation_split))
    val_size = max(1, min(n - 1, val_size))
    return idx[:-val_size], idx[-val_size:]


def host_shard(
    *arrays: np.ndarray,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> Tuple[np.ndarray, ...]:
    """Slice per-host rows: strided like tf.data's ``shard(n, id)``."""
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    if process_count <= 1:
        return arrays
    return tuple(a[process_index::process_count] for a in arrays)


class BatchIterator:
    """Infinite batches over host-local arrays with per-epoch reshuffle.

    The reference shuffles with a 3000-row buffer and repeats
    (``train_tf_ps.py:599-601``); with in-RAM arrays we can afford a full
    permutation per epoch, which is strictly better shuffling and still
    deterministic given the seed.
    """

    def __init__(
        self,
        arrays: Dict[str, np.ndarray],
        batch_size: int,
        shuffle: bool = True,
        seed: int = DEFAULT_SEED,
        drop_remainder: bool = True,
    ):
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"Array length mismatch: {sizes}")
        self.arrays = arrays
        self.n = next(iter(sizes.values()))
        self.batch_size = batch_size
        if self.n < batch_size and drop_remainder:
            raise ValueError(f"batch_size {batch_size} > dataset size {self.n}")
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self._seed = seed
        self._rng = np_rng(seed)
        self._order = np.arange(self.n)
        self._pos = self.n  # trigger reshuffle on first batch

    @property
    def steps_per_epoch(self) -> int:
        if self.drop_remainder:
            return max(1, self.n // self.batch_size)
        return -(-self.n // self.batch_size)  # ceil: remainder yields a partial batch

    def fast_forward(self, consumed_batches: int) -> "BatchIterator":
        """Rewind-and-replay to the state after ``consumed_batches``
        draws: resume-from-checkpoint continues the EXACT deterministic
        batch order mid-epoch instead of restarting a fresh epoch pass
        (which silently repeats some examples and starves others). Only
        the seeded shuffles are replayed — O(epochs), no data touched.
        Every host calls this with the same count, so host-sharded
        iterators stay in lockstep."""
        if consumed_batches < 0:
            raise ValueError(f"consumed_batches must be >= 0, "
                             f"got {consumed_batches}")
        spe = self.steps_per_epoch
        epochs_done, within = divmod(consumed_batches, spe)
        self._rng = np_rng(self._seed)
        self._order = np.arange(self.n)
        if self.shuffle:
            # one shuffle per STARTED epoch (the lazy reshuffle in
            # __next__ fires at each epoch's first draw)
            for _ in range(epochs_done + (1 if within else 0)):
                self._rng.shuffle(self._order)
        # within==0 → the next draw begins a new epoch (triggers its
        # shuffle); otherwise resume mid-epoch at the exact row offset
        self._pos = self.n if within == 0 else within * self.batch_size
        return self

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        epoch_exhausted = (
            self._pos + self.batch_size > self.n
            if self.drop_remainder
            else self._pos >= self.n
        )
        if epoch_exhausted:
            if self.shuffle:
                self._rng.shuffle(self._order)
            self._pos = 0
        end = self._pos + self.batch_size
        if not self.drop_remainder:
            end = min(end, self.n)
        sel = self._order[self._pos : end]
        self._pos = end
        return {k: v[sel] for k, v in self.arrays.items()}


def put_global_batch(batch: Dict[str, np.ndarray], sharding: NamedSharding) -> Dict[str, jax.Array]:
    """Host-local batch dict → globally-sharded jax.Arrays.

    Each host passes its local slice; together they form the global batch,
    split over the mesh data axes. Single-host this is just a sharded
    device_put.
    """
    return {
        k: jax.make_array_from_process_local_data(sharding, v) for k, v in batch.items()
    }


def prefetch_to_device(
    batches: Iterator[Dict[str, np.ndarray]],
    sharding: NamedSharding,
    size: int = 2,
) -> Iterator[Dict[str, jax.Array]]:
    """Stream ``put_global_batch``-ed batches with a background thread
    keeping up to ``size`` batches resident on device ahead of the
    consumer — host→device transfer overlaps the previous step's compute
    (the tf.data ``prefetch(AUTOTUNE)`` analog, ``train_tf_ps.py:322``,
    but placing *sharded global* arrays). ``size=0`` degrades to inline
    transfer. Exceptions in the source iterator re-raise at the consumer.

    The queue's occupancy is exported as the ``data_prefetch_queue_depth``
    obs gauge (sampled at each producer put and consumer get): a scrape
    reading 0 while steps run means the input pipeline is the
    bottleneck (input-starved steps); pinned at ``size`` means the
    device is — the signal that separates feed-rate problems from
    HBM/compute-bound ones in the shared metrics plane.
    """
    if size <= 0:
        for b in batches:
            yield put_global_batch(b, sharding)
        return

    import queue
    import threading

    from pyspark_tf_gke_tpu.obs.metrics import platform_families

    depth_gauge = platform_families()["data_prefetch_queue_depth"]

    q: "queue.Queue" = queue.Queue(maxsize=size)
    done = object()
    stop = threading.Event()

    def put_or_abort(item) -> bool:
        """Blocking put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                depth_gauge.set(q.qsize())
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for b in batches:
                if not put_or_abort(put_global_batch(b, sharding)):
                    return
            put_or_abort(done)
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            put_or_abort(e)

    t = threading.Thread(target=worker, daemon=True, name="device-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            depth_gauge.set(q.qsize())
            if item is done:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # Wait for the worker to actually stop: a caller may hand the
        # same source iterator to a new prefetcher (restart-with-resume),
        # and two threads on one generator is undefined.
        t.join()
