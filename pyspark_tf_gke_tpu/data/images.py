"""Image data path: flat directory + ``clean_labels.jsonl`` with (x_px, y_px)
regression targets — semantics matching the reference's image loader
(``workloads/raw-tf/train_tf_ps.py:168-322``):

* a jsonl line is used only if the file exists on disk, has a supported
  extension, and has both ``point.x_px`` and ``point.y_px``;
* images decode to 3 channels, resize with **tf.image.resize bilinear
  semantics** (half-pixel centers, antialias off — implemented first-party
  in ``resize_bilinear_tf``, golden-tested against tf) to (height, width),
  and scale to [0, 1] float32;
* targets are raw pixel coordinates in the *resized* space — no
  normalization (reference keeps original-pixel targets; see the
  commented-out rescale block at ``train_tf_ps.py:259-276``).

Decoding is host-side (PIL + numpy); the trainer moves ready batches to
device. The deterministic 80/20 split lives in ``data.pipeline`` so the
CSV and image paths share it.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import numpy as np
from PIL import Image

SUPPORTED_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".gif", ".ppm"}


def list_labeled_images(data_dir: str) -> Tuple[List[str], np.ndarray]:
    """Parse clean_labels.jsonl → (absolute file paths, [N,2] float32 targets)."""
    labels_path = os.path.join(data_dir, "clean_labels.jsonl")
    if not os.path.isfile(labels_path):
        raise RuntimeError(f"clean_labels.jsonl not found in: {data_dir}")

    filepaths: List[str] = []
    targets: List[List[float]] = []
    with open(labels_path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except Exception:
                continue
            name = str(obj.get("image", "")).strip()
            if not name:
                continue
            _, ext = os.path.splitext(name.lower())
            if ext not in SUPPORTED_EXTS:
                continue
            full_path = os.path.join(data_dir, name)
            if not os.path.isfile(full_path):
                continue
            point = obj.get("point") or {}
            x_px, y_px = point.get("x_px"), point.get("y_px")
            if x_px is None or y_px is None:
                continue
            filepaths.append(full_path)
            targets.append([float(x_px), float(y_px)])

    if not filepaths:
        raise RuntimeError("No valid labeled images were parsed from clean_labels.jsonl")
    return filepaths, np.asarray(targets, dtype=np.float32)


def count_images(data_dir: str) -> int:
    """Count usable labeled images (reference: ``train_tf_ps.py:168-199``)."""
    return len(list_labeled_images(data_dir)[0])


def resize_bilinear_tf(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """``tf.image.resize(method='bilinear')`` numerics in numpy:
    half-pixel centers, **no antialiasing** (the TF default). PIL's
    BILINEAR applies an antialias filter on downscale, which drifts
    pixel values vs the reference pipeline (``train_tf_ps.py:301-306``)
    — hence a first-party kernel instead of PIL. Separable lerp: the
    fractional weight comes from the unclamped floor; sample indices are
    clamped into range (matching TF's edge handling)."""
    img = img.astype(np.float32)
    in_h, in_w = img.shape[:2]

    def axis(n_in: int, n_out: int):
        if n_in == n_out:
            return None
        scale = n_in / n_out
        src = (np.arange(n_out, dtype=np.float32) + 0.5) * scale - 0.5
        lo_f = np.floor(src)
        frac = (src - lo_f).astype(np.float32)
        lo = np.clip(lo_f.astype(np.int64), 0, n_in - 1)
        hi = np.clip(lo_f.astype(np.int64) + 1, 0, n_in - 1)
        return lo, hi, frac

    rows = axis(in_h, height)
    if rows is not None:
        lo, hi, fr = rows
        img = img[lo] * (1.0 - fr)[:, None, None] + img[hi] * fr[:, None, None]
    cols = axis(in_w, width)
    if cols is not None:
        lo, hi, fr = cols
        img = img[:, lo] * (1.0 - fr)[None, :, None] + img[:, hi] * fr[None, :, None]
    return img


def load_image(path: str, height: int, width: int) -> np.ndarray:
    """Decode → RGB → TF-semantics bilinear resize → [0,1] float32."""
    with Image.open(path) as img:
        arr = np.asarray(img.convert("RGB"), dtype=np.float32)
    return resize_bilinear_tf(arr, height, width) / 255.0


def make_image_arrays(
    data_dir: str,
    image_size: Tuple[int, int],
    indices: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Materialize (images [N,H,W,3], targets [N,2]) for a subset of the
    dataset. Suitable for datasets that fit in host RAM (the reference's
    laser-spot set); larger sets stream through ``data.tfrecord``."""
    filepaths, targets = list_labeled_images(data_dir)
    if indices is not None:
        filepaths = [filepaths[i] for i in indices]
        targets = targets[indices]
    h, w = image_size
    # Parallel decode (the tf.data ``map(..., num_parallel_calls)``
    # analog — the reference's second-order hot path, SURVEY §3.1): PIL
    # decode and the numpy resize both release the GIL, so threads give
    # near-linear speedup on many-core hosts. ``ex.map`` preserves input
    # order — the materialized array is bit-identical to the serial
    # loop, so the seeded split/shuffle semantics are untouched.
    from concurrent.futures import ThreadPoolExecutor

    workers = min(32, os.cpu_count() or 4, max(len(filepaths), 1))
    with ThreadPoolExecutor(max_workers=workers) as ex:
        images = np.stack(list(ex.map(
            lambda p: load_image(p, h, w), filepaths)))
    return images, targets
