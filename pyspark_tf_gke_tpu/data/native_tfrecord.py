"""TFRecord pipeline on the first-party native IO plane.

Same contract as ``pyspark_tf_gke_tpu.data.tfrecord`` (the tf.data-backed
path) but with zero tensorflow dependency: framing + Example codec + the
threaded prefetch reader come from the C++ library
(``native/src/tfrecord_io.cc``), with the pure-Python codec
(``data/codec.py``) as last-resort fallback. This is the path the
training image uses — tensorflow stays a Spark-side-only dependency.

Semantics mirrored from the reference's input pipeline
(``/root/reference/workloads/raw-tf/train_tf_ps.py:301-322``):
file-level host sharding (the ``dataset.shard`` analog), a 3000-row
shuffle buffer, repeat, drop-remainder batching.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from pyspark_tf_gke_tpu.data.codec import Schema
from pyspark_tf_gke_tpu.utils.logging import get_logger
from pyspark_tf_gke_tpu.utils.seeding import DEFAULT_SEED, np_rng

logger = get_logger("data.native_tfrecord")


def native_available() -> bool:
    from pyspark_tf_gke_tpu import native

    return native.available()


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------


def write_tfrecord_shards(
    arrays: Dict[str, np.ndarray],
    path_prefix: str,
    num_shards: int = 4,
    schema: Optional[Schema] = None,
) -> Sequence[str]:
    """Write row-aligned arrays as TFRecord shards via the native codec
    (python-codec fallback). Same naming/striping as the tf.data writer:
    ``{prefix}-{i:05d}-of-{n:05d}.tfrecord``, row i -> shard i % n."""
    from pyspark_tf_gke_tpu.data.tfrecord import schema_for

    if schema is None:
        schema = schema_for(arrays)
    n = len(next(iter(arrays.values())))
    for k, v in arrays.items():
        if len(v) != n:
            raise ValueError(f"array {k!r} length {len(v)} != {n}")
    os.makedirs(os.path.dirname(os.path.abspath(path_prefix)), exist_ok=True)

    use_native = native_available()
    if use_native:
        from pyspark_tf_gke_tpu import native as io
    else:
        from pyspark_tf_gke_tpu.data import codec as io  # type: ignore[no-redef]
        logger.warning("native IO unavailable; using pure-Python codec")

    paths = []
    for shard in range(num_shards):
        path = f"{path_prefix}-{shard:05d}-of-{num_shards:05d}.tfrecord"
        paths.append(path)
        if use_native:
            with io.RecordWriter(path) as w:
                for i in range(shard, n, num_shards):
                    row = {k: arrays[k][i] for k in schema}
                    w.write(io.encode_example(schema, row))
        else:
            from pyspark_tf_gke_tpu.data.codec import encode_example, encode_record

            with open(path, "wb") as f:
                for i in range(shard, n, num_shards):
                    row = {k: arrays[k][i] for k in schema}
                    f.write(encode_record(encode_example(schema, row)))
    return paths


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------


def _iter_rows(
    files: Sequence[str], schema: Schema, nthreads: int, read_batch: int
) -> Iterator[Dict[str, np.ndarray]]:
    """Stream decoded row-blocks from the shard set."""
    if native_available():
        from pyspark_tf_gke_tpu.native import ExamplePool

        with ExamplePool(files, schema, nthreads=nthreads) as pool:
            while True:
                block = pool.next_rows(read_batch)
                if block is None:
                    return
                yield block
    else:
        from pyspark_tf_gke_tpu.data.codec import iter_records, parse_example

        rows = []
        for path in files:
            for rec in iter_records(path):
                rows.append(parse_example(schema, rec))
                if len(rows) == read_batch:
                    yield {
                        k: np.stack([r[k] for r in rows]) for k in schema
                    }
                    rows = []
        if rows:
            yield {k: np.stack([r[k] for r in rows]) for k in schema}


class ShuffleBuffer:
    """Fixed-capacity reservoir shuffle, the tf.data ``shuffle(buffer)``
    analog (reference uses buffer 3000, train_tf_ps.py:599)."""

    def __init__(self, capacity: int, seed: int = DEFAULT_SEED):
        self.capacity = capacity
        self._rng = np_rng(seed)
        self._rows: list = []

    def push_pop(self, row) -> Optional[object]:
        if len(self._rows) < self.capacity:
            self._rows.append(row)
            return None
        j = int(self._rng.integers(len(self._rows)))
        out = self._rows[j]
        self._rows[j] = row
        return out

    def drain(self) -> Iterator[object]:
        order = self._rng.permutation(len(self._rows))
        for j in order:
            yield self._rows[j]
        self._rows = []


def read_tfrecord_batches(
    pattern: str,
    schema: Schema,
    batch_size: int,
    shuffle: bool = True,
    seed: int = DEFAULT_SEED,
    repeat: bool = True,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    nthreads: int = 4,
    shuffle_buffer: int = 3000,
    int_dtype=np.int32,
) -> Iterator[Dict[str, np.ndarray]]:
    """Stream host-sharded numpy batches from TFRecord shards, natively.

    Drop-in replacement for ``data.tfrecord.read_tfrecord_batches`` —
    same file-level host sharding (sorted files striped over processes)
    and the same cast of int features to int32 that the tf.data parse fn
    applies.
    """
    import jax

    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()

    from pyspark_tf_gke_tpu.utils.fs import fs_glob, spool_local

    files = fs_glob(pattern)
    if not files:
        raise FileNotFoundError(f"no TFRecord shards match {pattern!r}")
    local_files = files[process_index::process_count]
    if not local_files:
        raise ValueError(
            f"{len(files)} shards < {process_count} processes; write more shards"
        )
    # The C++ reader (native/src/tfrecord_io.cc) is fopen-based —
    # gs://-and-friends stage through the local spool once, then every
    # epoch reads locally. Sharding happens BEFORE spooling: each host
    # downloads only its own shards.
    local_files = [spool_local(f) for f in local_files]

    def cast(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = {}
        for k, (kind, _) in schema.items():
            v = batch[k]
            out[k] = v.astype(int_dtype) if kind == "int" else v
        return out

    pending: Dict[str, list] = {k: [] for k in schema}
    pending_rows = 0

    def emit_ready() -> Iterator[Dict[str, np.ndarray]]:
        nonlocal pending, pending_rows
        while pending_rows >= batch_size:
            batch = {}
            for k in schema:
                stacked = (
                    pending[k][0]
                    if len(pending[k]) == 1
                    else np.concatenate(pending[k])
                )
                batch[k] = stacked[:batch_size]
                pending[k] = [stacked[batch_size:]]
            pending_rows -= batch_size
            yield cast(batch)

    while True:  # epoch loop (single pass if not repeat)
        if shuffle:
            buf = ShuffleBuffer(shuffle_buffer, seed=seed)
            seed += 1  # reshuffle differently each epoch, deterministically

            def rows():
                for block in _iter_rows(local_files, schema, nthreads, batch_size):
                    n = len(next(iter(block.values())))
                    for i in range(n):
                        out = buf.push_pop({k: block[k][i] for k in schema})
                        if out is not None:
                            yield out
                yield from buf.drain()

            row_iter = rows()
            stash: list = []
            for row in row_iter:
                stash.append(row)
                if len(stash) == batch_size:
                    yield cast({k: np.stack([r[k] for r in stash]) for k in schema})
                    stash = []
            # drop remainder (parity with drop_remainder=True)
        else:
            for block in _iter_rows(local_files, schema, nthreads, batch_size):
                for k in schema:
                    pending[k].append(block[k])
                pending_rows += len(next(iter(block.values())))
                yield from emit_ready()
            pending = {k: [] for k in schema}
            pending_rows = 0
        if not repeat:
            return
