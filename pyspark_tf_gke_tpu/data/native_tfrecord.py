"""TFRecord pipeline on the first-party native IO plane.

Same contract as ``pyspark_tf_gke_tpu.data.tfrecord`` (the tf.data-backed
path) but with zero tensorflow dependency: framing + Example codec + the
threaded prefetch reader come from the C++ library
(``native/src/tfrecord_io.cc``), with the pure-Python codec
(``data/codec.py``) as last-resort fallback. This is the path the
training image uses — tensorflow stays a Spark-side-only dependency.

Semantics mirrored from the reference's input pipeline
(``/root/reference/workloads/raw-tf/train_tf_ps.py:301-322``):
file-level host sharding (the ``dataset.shard`` analog), a 3000-row
shuffle buffer, repeat, drop-remainder batching.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from pyspark_tf_gke_tpu.data.codec import Schema
from pyspark_tf_gke_tpu.utils.logging import get_logger
from pyspark_tf_gke_tpu.utils.seeding import DEFAULT_SEED, np_rng

logger = get_logger("data.native_tfrecord")


def native_available() -> bool:
    from pyspark_tf_gke_tpu import native

    return native.available()


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------


def _write_one_shard(arrays: Dict[str, np.ndarray], schema: Schema,
                     path: str, shard: int, num_shards: int,
                     use_native: bool) -> None:
    """Write shard ``shard`` (rows ``shard::num_shards``) to ``path`` —
    the per-shard body both the serial and threaded writers run, so
    their outputs are byte-identical."""
    n = len(next(iter(arrays.values())))
    if use_native:
        from pyspark_tf_gke_tpu import native as io

        with io.RecordWriter(path) as w:
            for i in range(shard, n, num_shards):
                row = {k: arrays[k][i] for k in schema}
                w.write(io.encode_example(schema, row))
    else:
        from pyspark_tf_gke_tpu.data.codec import encode_example, encode_record

        with open(path, "wb") as f:
            for i in range(shard, n, num_shards):
                row = {k: arrays[k][i] for k in schema}
                f.write(encode_record(encode_example(schema, row)))


def write_tfrecord_shards(
    arrays: Dict[str, np.ndarray],
    path_prefix: str,
    num_shards: int = 4,
    schema: Optional[Schema] = None,
    num_workers: Optional[int] = None,
) -> Sequence[str]:
    """Write row-aligned arrays as TFRecord shards via the native codec
    (python-codec fallback). Same naming/striping as the tf.data writer:
    ``{prefix}-{i:05d}-of-{n:05d}.tfrecord``, row i -> shard i % n.

    Shards are independent row stripes, so they write CONCURRENTLY: one
    worker thread per shard up to ``num_workers`` (default
    ``min(num_shards, cpu_count)``; 1 = the serial path). Output bytes
    are identical either way — the parallel writer is a pure throughput
    change (``bench.py io`` A/Bs it; the native writer's encode/IO path
    releases the GIL so threads genuinely overlap). A worker exception
    cancels the write and re-raises at the caller with the shard's
    partial file removed — matching the ``data/pipeline.py`` prefetch
    relay contract: no silent half-written shard can reach a manifest.
    """
    from pyspark_tf_gke_tpu.data.tfrecord import schema_for

    if schema is None:
        schema = schema_for(arrays)
    n = len(next(iter(arrays.values())))
    for k, v in arrays.items():
        if len(v) != n:
            raise ValueError(f"array {k!r} length {len(v)} != {n}")
    os.makedirs(os.path.dirname(os.path.abspath(path_prefix)), exist_ok=True)

    use_native = native_available()
    if not use_native:
        logger.warning("native IO unavailable; using pure-Python codec")

    paths = [f"{path_prefix}-{shard:05d}-of-{num_shards:05d}.tfrecord"
             for shard in range(num_shards)]
    if num_workers is None:
        num_workers = min(num_shards, os.cpu_count() or 1)
    num_workers = max(1, min(int(num_workers), num_shards))

    if num_workers == 1:
        for shard, path in enumerate(paths):
            try:
                _write_one_shard(arrays, schema, path, shard, num_shards,
                                 use_native)
            except BaseException:
                try:  # same no-torn-shard contract as the threaded path
                    os.remove(path)
                except OSError:
                    pass
                raise
        return paths

    import queue
    import threading

    todo: "queue.Queue" = queue.Queue()
    for shard in range(num_shards):
        todo.put(shard)
    errors: list = []
    err_lock = threading.Lock()

    def worker() -> None:
        while True:
            try:
                shard = todo.get_nowait()
            except queue.Empty:
                return
            with err_lock:
                if errors:  # a sibling failed: stop dequeuing work
                    return
            try:
                _write_one_shard(arrays, schema, paths[shard], shard,
                                 num_shards, use_native)
            except BaseException as exc:  # noqa: BLE001 — relayed below
                try:  # never leave a torn shard behind
                    os.remove(paths[shard])
                except OSError:
                    pass
                with err_lock:
                    errors.append(exc)
                return

    threads = [threading.Thread(target=worker, name=f"shard-writer-{i}",
                                daemon=True)
               for i in range(num_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return paths


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------


def _iter_rows(
    files: Sequence[str], schema: Schema, nthreads: int, read_batch: int
) -> Iterator[Dict[str, np.ndarray]]:
    """Stream decoded row-blocks from the shard set."""
    if native_available():
        from pyspark_tf_gke_tpu.native import ExamplePool

        with ExamplePool(files, schema, nthreads=nthreads) as pool:
            while True:
                block = pool.next_rows(read_batch)
                if block is None:
                    return
                yield block
    else:
        from pyspark_tf_gke_tpu.data.codec import iter_records, parse_example

        rows = []
        for path in files:
            for rec in iter_records(path):
                rows.append(parse_example(schema, rec))
                if len(rows) == read_batch:
                    yield {
                        k: np.stack([r[k] for r in rows]) for k in schema
                    }
                    rows = []
        if rows:
            yield {k: np.stack([r[k] for r in rows]) for k in schema}


class ShuffleBuffer:
    """Fixed-capacity reservoir shuffle, the tf.data ``shuffle(buffer)``
    analog (reference uses buffer 3000, train_tf_ps.py:599)."""

    def __init__(self, capacity: int, seed: int = DEFAULT_SEED):
        self.capacity = capacity
        self._rng = np_rng(seed)
        self._rows: list = []

    def push_pop(self, row) -> Optional[object]:
        if len(self._rows) < self.capacity:
            self._rows.append(row)
            return None
        j = int(self._rng.integers(len(self._rows)))
        out = self._rows[j]
        self._rows[j] = row
        return out

    def drain(self) -> Iterator[object]:
        order = self._rng.permutation(len(self._rows))
        for j in order:
            yield self._rows[j]
        self._rows = []


def read_tfrecord_batches(
    pattern: str,
    schema: Schema,
    batch_size: int,
    shuffle: bool = True,
    seed: int = DEFAULT_SEED,
    repeat: bool = True,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    nthreads: int = 4,
    shuffle_buffer: int = 3000,
    int_dtype=np.int32,
) -> Iterator[Dict[str, np.ndarray]]:
    """Stream host-sharded numpy batches from TFRecord shards, natively.

    Drop-in replacement for ``data.tfrecord.read_tfrecord_batches`` —
    same file-level host sharding (sorted files striped over processes)
    and the same cast of int features to int32 that the tf.data parse fn
    applies.
    """
    import jax

    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()

    from pyspark_tf_gke_tpu.utils.fs import fs_glob, spool_local

    files = fs_glob(pattern)
    if not files:
        raise FileNotFoundError(f"no TFRecord shards match {pattern!r}")
    local_files = files[process_index::process_count]
    if not local_files:
        raise ValueError(
            f"{len(files)} shards < {process_count} processes; write more shards"
        )
    # The C++ reader (native/src/tfrecord_io.cc) is fopen-based —
    # gs://-and-friends stage through the local spool once, then every
    # epoch reads locally. Sharding happens BEFORE spooling: each host
    # downloads only its own shards.
    local_files = [spool_local(f) for f in local_files]

    def cast(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = {}
        for k, (kind, _) in schema.items():
            v = batch[k]
            out[k] = v.astype(int_dtype) if kind == "int" else v
        return out

    pending: Dict[str, list] = {k: [] for k in schema}
    pending_rows = 0

    def emit_ready() -> Iterator[Dict[str, np.ndarray]]:
        nonlocal pending, pending_rows
        while pending_rows >= batch_size:
            batch = {}
            for k in schema:
                stacked = (
                    pending[k][0]
                    if len(pending[k]) == 1
                    else np.concatenate(pending[k])
                )
                batch[k] = stacked[:batch_size]
                pending[k] = [stacked[batch_size:]]
            pending_rows -= batch_size
            yield cast(batch)

    while True:  # epoch loop (single pass if not repeat)
        if shuffle:
            buf = ShuffleBuffer(shuffle_buffer, seed=seed)
            seed += 1  # reshuffle differently each epoch, deterministically

            def rows():
                for block in _iter_rows(local_files, schema, nthreads, batch_size):
                    n = len(next(iter(block.values())))
                    for i in range(n):
                        out = buf.push_pop({k: block[k][i] for k in schema})
                        if out is not None:
                            yield out
                yield from buf.drain()

            row_iter = rows()
            stash: list = []
            for row in row_iter:
                stash.append(row)
                if len(stash) == batch_size:
                    yield cast({k: np.stack([r[k] for r in stash]) for k in schema})
                    stash = []
            # drop remainder (parity with drop_remainder=True)
        else:
            for block in _iter_rows(local_files, schema, nthreads, batch_size):
                for k in schema:
                    pending[k].append(block[k])
                pending_rows += len(next(iter(block.values())))
                yield from emit_ready()
            pending = {k: [] for k in schema}
            pending_rows = 0
        if not repeat:
            return


# ---------------------------------------------------------------------------
# manifest tailing (the continuous pipeline's trainer-side data source)
# ---------------------------------------------------------------------------


class ManifestTailSource:
    """Infinite batch iterator tailing a growing shard-set manifest.

    The continuous pipeline's trainer-side hand-off: the ETL side
    appends completed shard generations to a
    :class:`~pyspark_tf_gke_tpu.pipeline.manifest.ShardSetManifest`;
    this source re-reads the manifest at every **epoch boundary**, so
    shards landed mid-epoch join the NEXT epoch's pass (an epoch is one
    deterministic shuffled pass over the shard set present when it
    started — the ``dataset.shard``+``repeat`` analog, made growable).

    Determinism + resume: epoch ``e`` shuffles with ``seed + e`` through
    a :class:`~pyspark_tf_gke_tpu.data.pipeline.BatchIterator`, and
    ``consumed_batches`` counts every draw. Re-creating the source with
    a persisted ``consumed_batches`` replays epoch lengths against the
    CURRENT manifest and ``fast_forward``s into the interrupted epoch —
    a coordinator restart resumes the exact batch stream mid-epoch
    whenever the manifest hasn't grown since the crash (and a
    consistent, freshly-shuffled stream when it has).

    Host-sharding mirrors :func:`read_tfrecord_batches`: sorted shards
    striped over processes, each host reading only its own files.
    """

    def __init__(self, manifest_path: str, schema: Schema,
                 batch_size: int, *, shuffle: bool = True,
                 seed: int = DEFAULT_SEED, consumed_batches: int = 0,
                 wait_timeout_s: float = 60.0, poll_s: float = 0.1,
                 nthreads: int = 1, int_dtype=np.int32,
                 process_index: int = 0, process_count: int = 1):
        # nthreads defaults to 1: exact-resume REQUIRES a deterministic
        # row order, and the native ExamplePool interleaves shard
        # blocks nondeterministically with >1 reader thread — the
        # seeded BatchIterator shuffle then permutes DIFFERENT
        # underlying rows run to run, silently breaking the
        # replay-identical contract (and its test) ~1 run in 8.
        # Epoch loads are once-per-epoch; determinism outranks read
        # parallelism here. Callers that don't resume may raise it.
        from pyspark_tf_gke_tpu.pipeline.manifest import ShardSetManifest

        self.manifest = ShardSetManifest(manifest_path)
        self.schema = schema
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = int(seed)
        self.wait_timeout_s = float(wait_timeout_s)
        self.poll_s = float(poll_s)
        self.nthreads = int(nthreads)
        self.int_dtype = int_dtype
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.consumed_batches = 0
        self.epoch = 0
        self.data_generation = 0  # manifest generation the epoch saw
        self._it: Optional["BatchIterator"] = None
        self._remaining = 0
        self._fast_forward(int(consumed_batches))

    # -- internals ------------------------------------------------------

    def _load_rows(self) -> Dict[str, np.ndarray]:
        """All rows of this host's stripe of the CURRENT shard set,
        blocking (bounded) until the manifest holds at least one full
        batch for it."""
        import time as _time

        deadline = _time.monotonic() + self.wait_timeout_s
        while True:
            gen = self.manifest.generation()
            shards = self.manifest.shards()
            local = sorted(shards)[self.process_index::self.process_count]
            rows: Dict[str, list] = {k: [] for k in self.schema}
            count = 0
            for block in (_iter_rows(local, self.schema, self.nthreads,
                                     max(self.batch_size, 256))
                          if local else ()):
                for k in self.schema:
                    rows[k].append(block[k])
                count += len(next(iter(block.values())))
            if count >= self.batch_size:
                self.data_generation = gen
                out = {}
                for k, (kind, _) in self.schema.items():
                    stacked = (rows[k][0] if len(rows[k]) == 1
                               else np.concatenate(rows[k]))
                    out[k] = (stacked.astype(self.int_dtype)
                              if kind == "int" else stacked)
                return out
            if _time.monotonic() >= deadline:
                raise FileNotFoundError(
                    f"manifest {self.manifest.path} holds {count} row(s) "
                    f"for host {self.process_index}/{self.process_count} "
                    f"(< batch_size {self.batch_size}) after "
                    f"{self.wait_timeout_s}s")
            _time.sleep(self.poll_s)

    def _start_epoch(self) -> None:
        from pyspark_tf_gke_tpu.data.pipeline import BatchIterator

        arrays = self._load_rows()
        self._it = BatchIterator(arrays, self.batch_size,
                                 shuffle=self.shuffle,
                                 seed=self.seed + self.epoch)
        self._remaining = self._it.steps_per_epoch

    def _fast_forward(self, consumed: int) -> None:
        """Replay ``consumed`` draws' worth of epoch bookkeeping against
        the current manifest, landing mid-epoch via
        ``BatchIterator.fast_forward``."""
        if consumed < 0:
            raise ValueError(f"consumed_batches must be >= 0, "
                             f"got {consumed}")
        self._start_epoch()
        # the manifest is fixed for the duration of this replay, so
        # every replayed epoch has the SAME length — skip whole epochs
        # arithmetically (one shard-set reload at the final epoch for
        # its seed) instead of re-reading the data once per epoch
        spe = self._it.steps_per_epoch
        skip_epochs, left = divmod(consumed, spe)
        if skip_epochs:
            self.epoch += skip_epochs
            self._start_epoch()
        if left:
            self._it.fast_forward(left)
            self._remaining -= left
        self.consumed_batches = int(consumed)

    # -- iteration ------------------------------------------------------

    def __iter__(self) -> "ManifestTailSource":
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        if self._remaining <= 0:
            # epoch boundary: re-read the manifest — generations landed
            # mid-epoch join this new pass
            self.epoch += 1
            self._start_epoch()
        batch = next(self._it)
        self._remaining -= 1
        self.consumed_batches += 1
        return batch
