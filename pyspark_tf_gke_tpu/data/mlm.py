"""Masked-language-model example preparation (BERT pretraining recipe).

Host-side, deterministic: 15% of non-special tokens are selected per
row; of those 80% become ``[MASK]``, 10% a uniformly random token, 10%
stay unchanged. Labels carry the original token id at selected
positions and ``IGNORE_INDEX`` elsewhere, so the loss reduces over
masked positions only.

No counterpart in the reference (no language models there — SURVEY
§2b); the recipe follows the public BERT objective so the
``BertForPretraining`` MLM head (``models/bert.py``) is trainable
end-to-end, completing the pretrain+finetune story for config 5.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

IGNORE_INDEX = -100

# bert-base-uncased special-token ids (overridable per call)
DEFAULT_MASK_ID = 103   # [MASK]
DEFAULT_SPECIAL_IDS = (0, 101, 102)  # [PAD], [CLS], [SEP]


def apply_mlm_masking(
    input_ids: np.ndarray,           # [B, S] int
    vocab_size: int,
    rng: np.random.Generator,
    mask_prob: float = 0.15,
    mask_token_id: int = DEFAULT_MASK_ID,
    special_ids: Sequence[int] = DEFAULT_SPECIAL_IDS,
    attention_mask: Optional[np.ndarray] = None,  # [B, S] 1=real token
) -> Tuple[np.ndarray, np.ndarray]:
    """Returns ``(masked_ids, labels)``; both [B, S] int32."""
    ids = np.asarray(input_ids)
    candidates = ~np.isin(ids, np.asarray(special_ids))
    if attention_mask is not None:
        candidates &= np.asarray(attention_mask).astype(bool)

    selected = candidates & (rng.random(ids.shape) < mask_prob)
    labels = np.where(selected, ids, IGNORE_INDEX).astype(np.int32)

    action = rng.random(ids.shape)
    masked = ids.copy()
    masked[selected & (action < 0.8)] = mask_token_id
    randomize = selected & (action >= 0.8) & (action < 0.9)
    masked[randomize] = rng.integers(0, vocab_size, int(randomize.sum()))
    # remaining 10%: keep the original token
    return masked.astype(np.int32), labels


def mlm_batches(batches, vocab_size: int, seed: int = 1337,
                mask_prob: float = 0.15,
                mask_token_id: int = DEFAULT_MASK_ID) -> Iterator[Dict[str, np.ndarray]]:
    """Wrap an iterator of {input_ids, attention_mask, ...} batches into
    MLM training batches {input_ids, attention_mask, mlm_labels}."""
    rng = np.random.default_rng(seed)
    for batch in batches:
        masked, labels = apply_mlm_masking(
            batch["input_ids"], vocab_size, rng,
            mask_prob=mask_prob, mask_token_id=mask_token_id,
            attention_mask=batch.get("attention_mask"),
        )
        yield {
            "input_ids": masked,
            "attention_mask": batch.get(
                "attention_mask", np.ones_like(masked)),
            "mlm_labels": labels,
        }
