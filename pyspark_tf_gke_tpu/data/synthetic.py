"""Synthetic datasets for tests and benchmarks.

The reference's datasets (the 18k-row google-health CSV and the private
laser-spot image set) are not shipped here, so these generators produce
structurally identical stand-ins: a CSV with the same header/quirks
(missing values, nan strings), a flat image dir + ``clean_labels.jsonl``
with a bright synthetic "laser spot" whose center is the regression
target, classification arrays, and token batches for the BERT path.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Tuple

import numpy as np

from pyspark_tf_gke_tpu.utils.seeding import DEFAULT_SEED, np_rng

CSV_HEADER = (
    "edition,report_type,measure_name,state_name,subpopulation,value,lower_ci,upper_ci,source,source_date"
)

_MEASURES = ["Able-Bodied", "Asthma", "Cancer", "Child Poverty", "Premature Death"]
_SUBPOPS = ["Female", "Male", "Adults 18-44", "Adults 45-64", "Seniors 65+"]
_STATES = ["Alabama", "California", "New York", "Texas", "Utah"]


def make_synthetic_csv(path: str, rows: int = 500, missing_rate: float = 0.05,
                       seed: int = DEFAULT_SEED) -> str:
    rng = np_rng(seed)
    lines = [CSV_HEADER]
    for _ in range(rows):
        measure = _MEASURES[rng.integers(len(_MEASURES))]
        sub = _SUBPOPS[rng.integers(len(_SUBPOPS))]
        state = _STATES[rng.integers(len(_STATES))]
        value = rng.uniform(0, 100)
        lower, upper = value - rng.uniform(0, 5), value + rng.uniform(0, 5)
        fields = ["2023", "Annual", measure, state, sub,
                  f"{value:.2f}", f"{lower:.2f}", f"{upper:.2f}", "synthetic", "2023-01-01"]
        if rng.random() < missing_rate:  # reproduce the reference data's holes
            col = 4 + int(rng.integers(4))
            fields[col] = "" if rng.random() < 0.5 else "nan"
        lines.append(",".join(fields))
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    return path


def make_reference_csv(path: str, rows: int = 18154,
                       seed: int = DEFAULT_SEED) -> str:
    """Generate a ``health_disparities`` dataset at the reference's
    exact schema and scale (round-4 verdict, Missing #2).

    The reference checks in an 18,154-row CSV
    (``/root/reference/infra/local/mysql-database/datasets/csvs/health.csv``;
    DDL ``load_csv.py:32-69``) whose *shape quirks* exercise the whole
    ETL semantic chain. This generator reproduces those quirks from a
    measured profile of that file, with synthesized vocabularies:

    - constant ``edition`` / ``report_type`` columns (cardinality 1);
    - 30 measures, 52 states (incl. a national aggregate row label),
      16 subpopulations with the EMPTY subpopulation the most common
      value (~8%), matching the reference's 1,508 empty cells;
    - ``value`` empty on ~7% of rows and ``lower_ci``/``upper_ci``
      empty *together* on slightly more (CIs missing while the value is
      present) — the null-filter/imputation paths see realistic holes;
    - two dominant ``source`` strings CONTAINING COMMAS, so the CSV
      must be written quoted and every downstream parser is forced
      through real quoting (the reference's top source covers ~56% of
      rows); a handful of rows with an empty source;
    - ``source_date`` as year ranges ("2017-2019"-style, 6 distinct).

    Rows are value-synthetic (no reference data values are copied) —
    the schema, cardinalities, and hole rates are the contract.
    """
    rng = np_rng(seed)
    measures = [f"Measure {i:02d}" for i in range(28)] + [
        "Able-Bodied", "Premature Death"]  # a couple of realistic names
    states = [f"State {i:02d}" for i in range(51)] + ["United States"]
    subpops = [""] + [f"Subpop {i:02d}" for i in range(15)]
    # empty subpop most common, like the reference profile
    subpop_p = np.asarray([0.083] + [0.917 / 15] * 15)
    sources = [
        "Agency A, Survey of Record",          # comma → forced quoting
        "Bureau B, Community Survey PUMS",     # comma → forced quoting
        "Registry C",
        "Panel D Study",
        "Source E", "Source F", "Source G", "Source H", "Source I", "",
    ]
    source_p = np.asarray(
        [0.56, 0.30, 0.05, 0.03, 0.02, 0.015, 0.012, 0.008, 0.0045, 0.0005])
    dates = ["2017-2019", "2015-2019", "2019", "2018-2019", "2016-2018",
             "2020"]
    date_p = np.asarray([0.56, 0.37, 0.03, 0.02, 0.015, 0.005])

    import csv

    with open(path, "w", encoding="utf-8", newline="") as fh:
        w = csv.writer(fh)
        w.writerow(["edition", "report_type", "measure_name", "state_name",
                    "subpopulation", "value", "lower_ci", "upper_ci",
                    "source", "source_date"])
        for _ in range(rows):
            value = rng.uniform(0, 120)
            spread = rng.uniform(0.2, 8.0)
            cells_value = f"{value:.1f}"
            cells_lo = f"{max(value - spread, 0.0):.1f}"
            cells_hi = f"{value + spread:.1f}"
            r = rng.random()
            if r < 0.071:        # value AND CIs missing
                cells_value = cells_lo = cells_hi = ""
            elif r < 0.074:      # CIs missing, value present
                cells_lo = cells_hi = ""
            w.writerow([
                "2021", "2021 Health Disparities",
                measures[rng.integers(len(measures))],
                states[rng.integers(len(states))],
                subpops[rng.choice(len(subpops), p=subpop_p)],
                cells_value, cells_lo, cells_hi,
                sources[rng.choice(len(sources), p=source_p)],
                dates[rng.choice(len(dates), p=date_p)],
            ])
    return path


def make_synthetic_image_dataset(
    data_dir: str,
    num_images: int = 32,
    height: int = 64,
    width: int = 80,
    seed: int = DEFAULT_SEED,
) -> str:
    """Flat dir of PNGs + clean_labels.jsonl, laser-spot style: dark frame
    with a bright gaussian blob at the (x_px, y_px) target."""
    from PIL import Image

    os.makedirs(data_dir, exist_ok=True)
    rng = np_rng(seed)
    yy, xx = np.mgrid[0:height, 0:width].astype(np.float32)
    lines = []
    for i in range(num_images):
        cx = float(rng.uniform(4, width - 4))
        cy = float(rng.uniform(4, height - 4))
        blob = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * 3.0 ** 2)))
        img = (blob[..., None] * np.array([255, 40, 40]) +
               rng.normal(8, 4, (height, width, 3))).clip(0, 255).astype(np.uint8)
        name = f"img_{i:04d}.png"
        Image.fromarray(img).save(os.path.join(data_dir, name))
        lines.append(json.dumps({
            "image": name,
            "point": {"x_px": cx, "y_px": cy},
            "image_size": {"width": width, "height": height},
        }))
    with open(os.path.join(data_dir, "clean_labels.jsonl"), "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    return data_dir


def synthetic_classification_arrays(
    n: int = 512, input_dim: int = 3, num_classes: int = 10, seed: int = DEFAULT_SEED
) -> Tuple[np.ndarray, np.ndarray]:
    """Linearly-separable-ish float features + int labels (MLP/CSV path)."""
    rng = np_rng(seed)
    centers = rng.normal(0, 3, (num_classes, input_dim))
    y = rng.integers(0, num_classes, n).astype(np.int32)
    x = centers[y] + rng.normal(0, 1, (n, input_dim))
    return x.astype(np.float32), y


def synthetic_tokens(
    batch: int = 8, seq_len: int = 128, vocab_size: int = 30522, seed: int = DEFAULT_SEED
) -> Dict[str, np.ndarray]:
    rng = np_rng(seed)
    return {
        "input_ids": rng.integers(0, vocab_size, (batch, seq_len)).astype(np.int32),
        "attention_mask": np.ones((batch, seq_len), dtype=np.int32),
        "labels": rng.integers(0, 2, (batch,)).astype(np.int32),
    }
