"""Text → token-id pipeline for causal-LM pretraining.

No counterpart in the reference (its data plane is CSV rows and PNG
images — SURVEY §2a); this closes the loop for the decoder-only model
family (``models/causal_lm.py``): raw text files (local or ``gs://`` via
``utils.fs``) become packed fixed-length ``input_ids`` batches.

Two tokenizers:

* ``ByteTokenizer`` — always available, dependency-free: UTF-8 bytes
  0..255 plus ``<pad>``/``<bos>``/``<eos>`` specials (vocab 259).
  Deterministic and reversible; the right default for tests and smoke
  runs.
* ``load_hf_tokenizer`` — gated adapter over ``transformers``
  ``AutoTokenizer`` (baked into the image) for real vocabularies
  (e.g. ``gpt2``, ``bert-base-uncased``). Import-gated so the data
  plane never hard-depends on it.

Packing follows the standard LM recipe: documents are concatenated with
``eos`` separators into one token stream, then cut into ``seq_len``
rows — no padding waste, every position trains. Static shapes
throughout (XLA-friendly batches).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from pyspark_tf_gke_tpu.utils.fs import fs_glob, fs_open


@dataclasses.dataclass(frozen=True)
class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..255 = bytes, then specials."""

    pad_id: int = 256
    bos_id: int = 257
    eos_id: int = 258

    @property
    def vocab_size(self) -> int:
        return 259

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode(
            "utf-8", errors="replace")


class HFTokenizerAdapter:
    """Uniform facade (encode/decode/vocab_size/eos_id) over a
    ``transformers`` tokenizer."""

    def __init__(self, tok):
        self._tok = tok
        self.eos_id = (tok.eos_token_id if tok.eos_token_id is not None
                       else tok.sep_token_id or 0)
        self.pad_id = tok.pad_token_id if tok.pad_token_id is not None else 0
        self.vocab_size = int(len(tok))

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids))


def load_hf_tokenizer(name_or_path: str) -> HFTokenizerAdapter:
    try:
        from transformers import AutoTokenizer
    except ImportError as exc:  # pragma: no cover - baked into the image
        raise ImportError(
            "transformers is required for --tokenizer other than 'byte'"
        ) from exc
    return HFTokenizerAdapter(AutoTokenizer.from_pretrained(name_or_path))


def get_tokenizer(spec: str = "byte"):
    """``byte`` → ByteTokenizer; anything else → HF AutoTokenizer name."""
    if spec in ("", "byte"):
        return ByteTokenizer()
    return load_hf_tokenizer(spec)


def iter_documents(pattern: str, *, process_index: int = 0,
                   process_count: int = 1) -> Iterator[str]:
    """Yield documents from text files matching ``pattern`` (local glob
    or fsspec URL — gs:// in production). A document is a
    blank-line-separated block; files are striped across hosts
    round-robin (file i → host i % process_count), the same
    by-file contract as the TFRecord shard readers."""
    paths = fs_glob(pattern)
    if not paths:
        raise FileNotFoundError(f"no text files match {pattern!r}")
    for i, path in enumerate(paths):
        if i % process_count != process_index:
            continue
        with fs_open(path, "rb") as fh:
            buf: List[str] = []
            for raw in fh:
                line = raw.decode("utf-8", errors="replace").rstrip("\n")
                if line.strip():
                    buf.append(line)
                elif buf:
                    yield "\n".join(buf)
                    buf = []
            if buf:
                yield "\n".join(buf)


def pack_tokens(
    docs: Iterable[str],
    tokenizer,
    seq_len: int,
    with_segments: bool = False,
) -> Iterator:
    """Concatenate tokenized docs with ``eos`` separators; emit
    fixed-length ``[seq_len]`` int32 rows. The trailing partial row is
    dropped (static shapes beat a padded straggler).

    ``with_segments=True`` yields ``(tokens, segment_ids)`` pairs where
    the segment id increments per document (an eos separator belongs to
    the document it ends) — attention can then be confined within
    documents (block-diagonal masking) instead of leaking across packed
    boundaries."""
    stream: List[int] = []
    seg_stream: List[int] = []
    eos = tokenizer.eos_id
    doc_id = 0
    for doc in docs:
        ids = tokenizer.encode(doc)
        stream.extend(ids)
        stream.append(eos)
        if with_segments:
            seg_stream.extend([doc_id] * (len(ids) + 1))
            doc_id += 1
        while len(stream) >= seq_len:
            row = np.asarray(stream[:seq_len], np.int32)
            del stream[:seq_len]
            if with_segments:
                segs = np.asarray(seg_stream[:seq_len], np.int32)
                del seg_stream[:seq_len]
                # per-row local ids (attention only compares equality)
                yield row, segs - segs[0]
            else:
                yield row


def lm_batches(
    pattern: str,
    tokenizer,
    seq_len: int,
    batch_size: int,
    *,
    seed: int = 0,
    repeat: bool = True,
    shuffle_buffer: int = 256,
    process_index: int = 0,
    process_count: int = 1,
    with_segments: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Packed LM batches ``{"input_ids": [B, S] int32}`` (plus
    ``"segment_ids"`` when ``with_segments`` — document-boundary
    attention masking).

    Rows pass through a reservoir-style shuffle buffer (seeded — the
    same determinism contract as the TFRecord readers); ``repeat``
    restarts the file pass with a reseeded buffer each epoch."""
    rng = np.random.default_rng(seed)
    epoch = 0
    batch: List = []  # partial batches carry across epochs

    def emit(batch):
        if with_segments:
            return {"input_ids": np.stack([t for t, _ in batch]),
                    "segment_ids": np.stack([s for _, s in batch])}
        return {"input_ids": np.stack(batch)}

    while True:
        buf: List = []
        produced = 0
        rows = pack_tokens(
            iter_documents(pattern, process_index=process_index,
                           process_count=process_count),
            tokenizer, seq_len, with_segments=with_segments)
        for row in rows:
            produced += 1
            if shuffle_buffer > 1:
                buf.append(row)
                if len(buf) < shuffle_buffer:
                    continue
                idx = rng.integers(0, len(buf))
                buf[idx], buf[-1] = buf[-1], buf[idx]
                row = buf.pop()
            batch.append(row)
            if len(batch) == batch_size:
                yield emit(batch)
                batch = []
        # index permutation, not rng.shuffle: buf rows may be tuples
        buf = [buf[i] for i in rng.permutation(len(buf))]
        for row in buf:
            batch.append(row)
            if len(batch) == batch_size:
                yield emit(batch)
                batch = []
        if produced == 0:
            # Empty pass: corpus too small for a single seq_len row, or
            # multi-host striping gave this process no files. Repeating
            # would busy-hang the trainer — fail loudly instead.
            raise ValueError(
                f"{pattern!r} produced no length-{seq_len} rows for "
                f"process {process_index}/{process_count}; corpus too "
                "small or too few files for the host count")
        if not repeat:
            return
        epoch += 1
        rng = np.random.default_rng(seed + epoch)
