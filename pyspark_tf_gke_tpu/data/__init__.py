from pyspark_tf_gke_tpu.data.csv_loader import load_csv, open_text
from pyspark_tf_gke_tpu.data.images import (
    count_images,
    list_labeled_images,
    load_image,
    make_image_arrays,
)
from pyspark_tf_gke_tpu.data.pipeline import (
    BatchIterator,
    host_shard,
    put_global_batch,
    train_validation_split,
)
from pyspark_tf_gke_tpu.data.synthetic import (
    make_synthetic_csv,
    make_synthetic_image_dataset,
    synthetic_classification_arrays,
    synthetic_tokens,
)

__all__ = [
    "load_csv",
    "open_text",
    "count_images",
    "list_labeled_images",
    "load_image",
    "make_image_arrays",
    "BatchIterator",
    "host_shard",
    "put_global_batch",
    "train_validation_split",
    "make_synthetic_csv",
    "make_synthetic_image_dataset",
    "synthetic_classification_arrays",
    "synthetic_tokens",
]
