"""TFRecord bridge: the contract between the Spark ETL pool and the TPU
training plane (BASELINE.json configs 3 and 5; SURVEY §7 step 7).

Schema contract (one tf.train.Example per row):
* float arrays   → ``float_list`` feature named after the column;
* int arrays     → ``int64_list``;
* uint8 tensors  → ``bytes_list`` raw bytes (shape restored by the reader
  from the declared schema).

The Spark side writes the same schema via
``etl.tfrecord_bridge.write_dataframe_shards``; this module is the
TPU-side reader (and a host-side writer used by tests and single-host
pipelines). Multi-host reads shard **by file** per process — the SPMD
analog of the reference's ``dataset.shard(num_input_pipelines, id)``
(``train_tf_ps.py:312-313``) — so hosts never read overlapping shards.

Import of tensorflow is deferred: the training image needs it only when
the TFRecord path is used.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

Schema = Dict[str, Tuple[str, Tuple[int, ...]]]  # name -> (kind, per-row shape)


def _tf():
    import tensorflow as tf

    return tf


def write_tfrecord_shards(
    arrays: Dict[str, np.ndarray],
    path_prefix: str,
    num_shards: int = 4,
) -> Sequence[str]:
    """Write row-aligned arrays as ``{path_prefix}-{i:05d}-of-{n:05d}.tfrecord``."""
    tf = _tf()
    n = len(next(iter(arrays.values())))
    for k, v in arrays.items():
        if len(v) != n:
            raise ValueError(f"array {k!r} length {len(v)} != {n}")
    os.makedirs(os.path.dirname(os.path.abspath(path_prefix)), exist_ok=True)

    paths = []
    for shard in range(num_shards):
        path = f"{path_prefix}-{shard:05d}-of-{num_shards:05d}.tfrecord"
        paths.append(path)
        with tf.io.TFRecordWriter(path) as writer:
            for i in range(shard, n, num_shards):
                feats = {}
                for key, arr in arrays.items():
                    row = arr[i]
                    if arr.dtype == np.uint8:
                        feats[key] = tf.train.Feature(
                            bytes_list=tf.train.BytesList(value=[row.tobytes()])
                        )
                    elif np.issubdtype(arr.dtype, np.integer):
                        feats[key] = tf.train.Feature(
                            int64_list=tf.train.Int64List(value=np.ravel(row).tolist())
                        )
                    else:
                        feats[key] = tf.train.Feature(
                            float_list=tf.train.FloatList(
                                value=np.ravel(row).astype(np.float32).tolist()
                            )
                        )
                ex = tf.train.Example(features=tf.train.Features(feature=feats))
                writer.write(ex.SerializeToString())
    return paths


def schema_for(arrays: Dict[str, np.ndarray]) -> Schema:
    out: Schema = {}
    for k, v in arrays.items():
        if v.dtype == np.uint8:
            kind = "bytes"
        elif np.issubdtype(v.dtype, np.integer):
            kind = "int"
        else:
            kind = "float"
        out[k] = (kind, tuple(v.shape[1:]))
    return out


def read_tfrecord_batches(
    pattern: str,
    schema: Schema,
    batch_size: int,
    shuffle: bool = True,
    seed: int = 1337,
    repeat: bool = True,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Stream host-sharded numpy batches from TFRecord shards.

    Files matching ``pattern`` are sorted and distributed round-robin over
    processes (file-level sharding: each host owns whole shards). Returns
    an infinite (if ``repeat``) iterator of dicts, ready for
    ``put_global_batch``.
    """
    import jax

    tf = _tf()
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()

    from pyspark_tf_gke_tpu.utils.fs import fs_glob, spool_local

    files = fs_glob(pattern)
    if not files:
        raise FileNotFoundError(f"no TFRecord shards match {pattern!r}")
    local_files = files[process_index::process_count]
    if not local_files:
        raise ValueError(
            f"{len(files)} shards < {process_count} processes; write more shards"
        )
    # tf.data reads gs:// natively (zero-copy); other remote schemes
    # (memory:// in tests) stage through the local spool.
    local_files = [
        f if f.startswith(("gs://", "gcs://")) else spool_local(f)
        for f in local_files
    ]

    feature_spec = {}
    for key, (kind, shape) in schema.items():
        if kind == "bytes":
            feature_spec[key] = tf.io.FixedLenFeature([], tf.string)
        elif kind == "int":
            feature_spec[key] = tf.io.FixedLenFeature(shape, tf.int64)
        else:
            feature_spec[key] = tf.io.FixedLenFeature(shape, tf.float32)

    def parse(raw):
        ex = tf.io.parse_single_example(raw, feature_spec)
        out = {}
        for key, (kind, shape) in schema.items():
            v = ex[key]
            if kind == "bytes":
                v = tf.reshape(tf.io.decode_raw(v, tf.uint8), shape)
            elif kind == "int":
                v = tf.cast(v, tf.int32)
            out[key] = v
        return out

    ds = tf.data.TFRecordDataset(local_files, num_parallel_reads=tf.data.AUTOTUNE)
    ds = ds.map(parse, num_parallel_calls=tf.data.AUTOTUNE)
    if shuffle:
        ds = ds.shuffle(buffer_size=3000, seed=seed)  # reference buffer size
    ds = ds.batch(batch_size, drop_remainder=True)
    if repeat:
        ds = ds.repeat()
    ds = ds.prefetch(tf.data.AUTOTUNE)

    for batch in ds.as_numpy_iterator():
        yield batch
