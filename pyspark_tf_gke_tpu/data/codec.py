"""Pure-Python TFRecord + tf.train.Example codec.

Fallback for environments without the native library *and* without
tensorflow, and the independent oracle the native C++ implementation
(``pyspark_tf_gke_tpu/native/src/tfrecord_io.cc``) is tested against.
Implements exactly the subset the framework's schema uses: CRC32C-masked
record framing, and Examples whose features are fixed-size
FloatList/Int64List/BytesList (the schema contract of
``pyspark_tf_gke_tpu.data.tfrecord``).
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, Tuple

import numpy as np

Schema = Dict[str, Tuple[str, Tuple[int, ...]]]

_KIND_DTYPE = {"float": np.float32, "int": np.int64, "bytes": np.uint8}

# ---------------------------------------------------------------------------
# crc32c
# ---------------------------------------------------------------------------

_CRC_TABLE = None


def _table():
    global _CRC_TABLE
    if _CRC_TABLE is None:
        tbl = np.empty(256, dtype=np.uint32)
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            tbl[i] = c
        _CRC_TABLE = tbl
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    tbl = _table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = int(tbl[(crc ^ b) & 0xFF]) ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# record framing
# ---------------------------------------------------------------------------


def encode_record(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (
        header
        + struct.pack("<I", masked_crc32c(header))
        + payload
        + struct.pack("<I", masked_crc32c(payload))
    )


def iter_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) != 12:
                raise ValueError(f"{path}: truncated record header")
            (length,) = struct.unpack("<Q", header[:8])
            (hcrc,) = struct.unpack("<I", header[8:])
            if masked_crc32c(header[:8]) != hcrc:
                raise ValueError(f"{path}: header CRC mismatch")
            payload = f.read(length)
            footer = f.read(4)
            if len(payload) != length or len(footer) != 4:
                raise ValueError(f"{path}: truncated record payload")
            if masked_crc32c(payload) != struct.unpack("<I", footer)[0]:
                raise ValueError(f"{path}: payload CRC mismatch")
            yield payload


# ---------------------------------------------------------------------------
# protobuf wire helpers
# ---------------------------------------------------------------------------


def _varint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    v = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not (b & 0x80):
            return v, pos
        shift += 7
        if shift >= 64:
            raise ValueError("varint overflow")


def _len_delim(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


# ---------------------------------------------------------------------------
# Example encode / parse
# ---------------------------------------------------------------------------


def encode_example(schema: Schema, row: Dict[str, np.ndarray]) -> bytes:
    features = b""
    for name, (kind, shape) in schema.items():
        arr = np.ascontiguousarray(row[name], dtype=_KIND_DTYPE[kind]).reshape(-1)
        if kind == "float":
            list_payload = _len_delim(1, arr.astype("<f4").tobytes())
        elif kind == "int":
            packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in arr)
            list_payload = _len_delim(1, packed)
        else:
            list_payload = _len_delim(1, arr.tobytes())
        kind_field = {"bytes": 1, "float": 2, "int": 3}[kind]
        feature = _len_delim(kind_field, list_payload)
        entry = _len_delim(1, name.encode()) + _len_delim(2, feature)
        features += _len_delim(1, entry)
    return _len_delim(1, features)


def _parse_submessages(buf: bytes):
    """Yield (field, wire, payload_or_value) for one message level."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2:
            n, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos : pos + n]
            pos += n
        elif wire == 0:
            v, pos = _read_varint(buf, pos)
            yield field, wire, v
        elif wire == 5:
            yield field, wire, buf[pos : pos + 4]
            pos += 4
        elif wire == 1:
            yield field, wire, buf[pos : pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _parse_list(kind: str, feature_buf: bytes) -> np.ndarray:
    want_field = {"bytes": 1, "float": 2, "int": 3}[kind]
    for field, wire, payload in _parse_submessages(feature_buf):
        if field != want_field or wire != 2:
            continue
        if kind == "float":
            vals = []
            for f2, w2, p2 in _parse_submessages(payload):
                if f2 != 1:
                    continue
                if w2 == 2:
                    vals.append(np.frombuffer(p2, dtype="<f4"))
                elif w2 == 5:
                    vals.append(np.frombuffer(p2, dtype="<f4"))
            return np.concatenate(vals) if vals else np.empty(0, np.float32)
        if kind == "int":
            vals = []
            for f2, w2, p2 in _parse_submessages(payload):
                if f2 != 1:
                    continue
                if w2 == 2:
                    pos = 0
                    while pos < len(p2):
                        v, pos = _read_varint(p2, pos)
                        vals.append(v)
                elif w2 == 0:
                    vals.append(p2)
            return np.array(vals, dtype=np.uint64).astype(np.int64)
        for f2, w2, p2 in _parse_submessages(payload):
            if f2 == 1 and w2 == 2:
                return np.frombuffer(p2, dtype=np.uint8)
        return np.empty(0, np.uint8)
    raise KeyError(f"feature has no {kind} list")


def parse_example(schema: Schema, record: bytes) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for field, wire, features_buf in _parse_submessages(record):
        if field != 1 or wire != 2:
            continue
        for f2, w2, entry in _parse_submessages(features_buf):
            if f2 != 1 or w2 != 2:
                continue
            key = None
            feature = None
            for f3, w3, p3 in _parse_submessages(entry):
                if f3 == 1 and w3 == 2:
                    key = p3.decode()
                elif f3 == 2 and w3 == 2:
                    feature = p3
            if key is None or feature is None or key not in schema:
                continue
            kind, shape = schema[key]
            arr = _parse_list(kind, feature)
            expect = int(np.prod(shape, dtype=np.int64)) or 1
            if arr.size != expect:
                raise ValueError(
                    f"feature {key!r}: got {arr.size} elements, schema says {expect}"
                )
            out[key] = arr.reshape(shape) if shape else arr.reshape(())
    missing = set(schema) - set(out)
    if missing:
        raise KeyError(f"record missing features: {sorted(missing)}")
    return out
