"""Replica HTTP client: cancellable requests + shared header parsing.

``urllib`` hides its socket, so a hedged request could not be cancelled
when its twin wins — this module talks :mod:`http.client` directly and
hands the caller a :class:`ReplicaCall` whose :meth:`ReplicaCall.cancel`
closes the underlying connection (the only cancel HTTP/1.1 has: the
replica sees the reset and its own deadline/timeout machinery reclaims
the slot).

:func:`parse_retry_after` is THE ``Retry-After`` parser — the gateway's
backpressure path and the round-trip tests both use it, so the engine's
429/503 responses (``train/serve.py`` ``RequestRejected``) can never
drift from what the router honors.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
from email.utils import parsedate_to_datetime
from typing import Optional, Tuple
from urllib.parse import urlsplit

from pyspark_tf_gke_tpu.chaos.inject import chaos_fire


class ReplicaUnreachable(RuntimeError):
    """Transport-level failure (connect refused/reset/timeout): the
    request never produced an HTTP status line, so it is SAFE to
    re-route — the alternative (an HTTP error status) means the replica
    saw the request and re-sending could duplicate work."""


def parse_retry_after(value: Optional[str],
                      default_s: float = 1.0) -> float:
    """Seconds to back off, from a ``Retry-After`` header value.

    Accepts the delta-seconds form (what ``train/serve.py`` sends) and
    the HTTP-date form; garbage or a missing header degrades to
    ``default_s`` — a malformed header from an overloaded replica must
    never crash the router's backpressure path, and backing off *some*
    amount is strictly safer than not backing off at all."""
    if value is None:
        return float(default_s)
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        import datetime

        when = parsedate_to_datetime(value)
        if when.tzinfo is None:
            when = when.replace(tzinfo=datetime.timezone.utc)
        now = datetime.datetime.now(datetime.timezone.utc)
        return max(0.0, (when - now).total_seconds())
    except (TypeError, ValueError):
        return float(default_s)


def sse_payload(line: bytes) -> Optional[str]:
    """The ``data:`` payload of one SSE line, or ``None`` for anything
    that isn't one (comments, ``id:`` lines, blank separators). ONE
    parser for the gateway's relay/splice loop and its journal replay —
    the framing the replica emits and the framing the resume path
    replays must never drift apart by copy."""
    line = line.strip()
    if not line.startswith(b"data:"):
        return None
    return line[5:].strip().decode("utf-8", errors="replace")


def split_base_url(base_url: str) -> Tuple[str, int]:
    """``http://host:port`` -> (host, port). The router speaks plain
    HTTP to replicas inside the cluster; a scheme other than http is a
    config error worth failing fast on."""
    parts = urlsplit(base_url if "//" in base_url else "//" + base_url)
    if parts.scheme not in ("", "http"):
        raise ValueError(f"replica URLs must be http:// ({base_url!r})")
    if not parts.hostname:
        raise ValueError(f"replica URL has no host: {base_url!r}")
    return parts.hostname, parts.port or 80


class ReplicaCall:
    """One in-flight HTTP request to a replica, cancellable from
    another thread. ``close``/``cancel`` are idempotent and safe to
    race with the reading thread — losing a hedge race closes the
    loser's socket mid-read and the reader surfaces
    :class:`ReplicaUnreachable`."""

    def __init__(self, base_url: str, timeout_s: float = 600.0):
        host, port = split_base_url(base_url)
        self._conn = http.client.HTTPConnection(host, port,
                                                timeout=timeout_s)
        self._lock = threading.Lock()
        self._cancelled = False
        self.response: Optional[http.client.HTTPResponse] = None

    def request(self, method: str, path: str,
                body: Optional[bytes] = None,
                headers: Optional[dict] = None) -> "ReplicaCall":
        hdrs = {"Content-Type": "application/json", **(headers or {})}
        try:
            if method == "POST":
                # chaos: the router.transport fault point — a fail
                # rule raises INSIDE this try, so it reaches the
                # caller as the same ReplicaUnreachable a dying pod
                # produces and exercises the REAL passive-health +
                # failover path (probes are GETs; they have their own
                # point in discovery.py)
                chaos_fire("router.transport", path=path)
            self._conn.request(method, path, body=body, headers=hdrs)
            self.response = self._conn.getresponse()
        except Exception as exc:  # noqa: BLE001 — one taxonomy: either
            # we were cancelled (hedge loser) or the replica is gone;
            # both are transport failures, not HTTP statuses
            self.close()
            raise ReplicaUnreachable(
                f"{method} {path} to replica failed before a status "
                f"line: {type(exc).__name__}: {exc}") from exc
        return self

    @property
    def status(self) -> int:
        assert self.response is not None
        return self.response.status

    def header(self, name: str) -> Optional[str]:
        assert self.response is not None
        return self.response.getheader(name)

    def read_json(self) -> dict:
        """Read + parse the full body. A replica dying mid-body is a
        transport failure (the status line alone proves nothing about a
        completed response)."""
        assert self.response is not None
        try:
            raw = self.response.read()
        except Exception as exc:  # noqa: BLE001
            raise ReplicaUnreachable(
                f"replica connection died mid-body: "
                f"{type(exc).__name__}: {exc}") from exc
        try:
            return json.loads(raw or b"{}")
        except ValueError as exc:
            raise ReplicaUnreachable(
                f"replica sent unparseable JSON ({len(raw)} bytes): "
                f"{exc}") from exc

    def iter_lines(self):
        """Yield response lines as bytes (SSE proxying). Raises
        :class:`ReplicaUnreachable` if the connection dies mid-stream —
        the caller decides whether any event already reached the client
        (re-route) or not (surface the terminal error)."""
        assert self.response is not None
        try:
            while True:
                line = self.response.readline()
                if not line:
                    return
                yield line
        except Exception as exc:  # noqa: BLE001
            raise ReplicaUnreachable(
                f"replica stream died: {type(exc).__name__}: "
                f"{exc}") from exc

    def cancel(self) -> None:
        """Abandon the call: shutdown + close the socket so a blocked
        read in the request thread unblocks NOW (a bare ``close`` does
        not reliably interrupt another thread's ``recv``). The replica
        sees a reset — its deadline/drain machinery reclaims the
        work."""
        with self._lock:
            self._cancelled = True
        try:
            sock = self._conn.sock
            if sock is not None:
                sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.close()

    @property
    def cancelled(self) -> bool:
        with self._lock:
            return self._cancelled

    def close(self) -> None:
        try:
            self._conn.close()
        except Exception:  # noqa: BLE001 — closing must never raise
            pass


def get_json(base_url: str, path: str,
             timeout_s: float = 5.0) -> Tuple[int, dict]:
    """One-shot GET -> (status, parsed body). Raises
    :class:`ReplicaUnreachable` on transport failure. Non-JSON bodies
    parse to {} — /healthz during startup may answer anything."""
    call = ReplicaCall(base_url, timeout_s=timeout_s)
    try:
        call.request("GET", path)
        status = call.status
        try:
            body = call.read_json()
        except ReplicaUnreachable:
            body = {}
        return status, body
    finally:
        call.close()
