"""The router's HTTP data plane: one gateway in front of N replicas.

Request lifecycle for ``POST /v1/generate`` (non-streamed):

1. **Route** — :func:`policy.choose_replica`: prefix-affinity target if
   it can absorb the work, else least-outstanding-tokens.
2. **Backpressure** — a 429/503 from the replica is an explicit "not
   now": honor its ``Retry-After`` (stop offering that replica work for
   that long), re-route ONCE to the next-best replica, and only if that
   one also sheds surface 429 to the client. One re-route, never a
   retry loop — the router must not amplify load into an overloaded
   fleet.
3. **Hedged failover** — past an adaptive delay (p99 of recent routed
   latencies, clamped to [--hedge-min-ms, --hedge-max-ms]) with no
   answer, fire the SAME request at a second replica and take whichever
   answers first; the loser's connection is closed (the HTTP-level
   cancel — the replica's own deadline/drain machinery reclaims the
   work). Generation here is deterministic-greedy or seeded sampling,
   so duplicated work is wasted compute, not wrong answers.
4. **Transport failure** — :class:`client.ReplicaUnreachable` (no HTTP
   status line) marks the replica DOWN immediately (passive health) and
   fails over to the next-best; this is what makes a SIGKILLed pod cost
   ~one probe interval, not a k8s Endpoints propagation delay.

Streams (``"stream": true``): a replica death BEFORE the first event
re-routes the whole request (nothing reached the client yet); after the
first event the router SPLICES: it has journaled every token event it
relayed (``router/journal.py``), so it builds a continuation request —
the original prompt plus the emitted TOKEN IDS (``continuation:
{emitted_ids}``; ids, not re-tokenized text, so the splice is exact
even for byte runs that don't round-trip through UTF-8),
``max_new_tokens`` reduced by the emitted count, the original deadline
still enforced from first submit — routes it to the next-best replica
(prefix affinity means the warm radix cache absorbs most of the
re-prefill) and relays the continuation into the SAME open SSE
connection; greedy decode makes the spliced stream token-exact. Resumes are capped by ``--stream-resume-max``
(default 1, consistent with the single re-route); past the cap the
explicit error terminal + ``[DONE]`` surfaces as before. Every SSE
event carries an ``id: <seq>`` line, and a client that lost its
connection to the ROUTER can replay from ``Last-Event-ID`` +
``X-Request-Id`` against the journal — the router keeps draining the
still-live upstream leg after a client hang-up, so a router↔client
blip doesn't kill the request either. Non-streamed ``/v1/generate``
accepts ``X-Idempotency-Key``: a retry after an ambiguous 502 replays
the cached verdict instead of generating twice.
"""

from __future__ import annotations

import argparse
import hmac
import itertools
import json
import os
import sys
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

from pyspark_tf_gke_tpu.obs.events import get_event_log
from pyspark_tf_gke_tpu.obs.export import handle_obs_request
from pyspark_tf_gke_tpu.obs.metrics import get_registry, router_families
from pyspark_tf_gke_tpu.obs.trace import TraceRecorder, use_span
from pyspark_tf_gke_tpu.router.client import (
    ReplicaCall,
    ReplicaUnreachable,
    parse_retry_after,
    sse_payload,
)
from pyspark_tf_gke_tpu.router.journal import (
    DONE as JOURNAL_DONE,
    FAILED as JOURNAL_FAILED,
    LIVE as JOURNAL_LIVE,
    IdempotencyCache,
    StreamJournal,
)
from pyspark_tf_gke_tpu.router.discovery import (
    DOWN,
    HealthProber,
    Replica,
    ReplicaSet,
    parse_replica_list,
    resolve_dns_replicas,
)
from pyspark_tf_gke_tpu.router.policy import (
    affinity_key,
    choose_replica,
    pick_prefill,
    split_by_role,
)
from pyspark_tf_gke_tpu.router.watchtower import (
    DEFAULT_ALERT_WINDOWS,
    Watchtower,
    parse_slo_spec,
)
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("router.gateway")

MAX_BODY_BYTES = 8 << 20  # mirror the replica's cap: reject before proxy


class _LatencyWindow:
    """Ring of recent routed-request latencies; p99 drives the hedge
    delay. Until ``min_samples`` land the estimate is the max clamp —
    hedging on no evidence would double cold-start compile traffic."""

    def __init__(self, size: int = 256, min_samples: int = 20):
        self._lock = threading.Lock()
        self._window = deque(maxlen=size)
        self.min_samples = min_samples

    def observe(self, ms: float) -> None:
        with self._lock:
            self._window.append(float(ms))

    def p99_ms(self) -> Optional[float]:
        with self._lock:
            if len(self._window) < self.min_samples:
                return None
            xs = sorted(self._window)
        return xs[min(len(xs) - 1, int(0.99 * (len(xs) - 1)))]


class _DisaggFallback(RuntimeError):
    """A KV-page handoff leg failed or was not worth finishing — the
    request falls back to the normal (RECOMPUTE) routing path."""


class RouterServer:
    """Route/forward engine behind the HTTP handler (transport-free so
    tests drive it directly)."""

    def __init__(self, replicas: List[Replica], *,
                 affinity_tokens: int = 32,
                 inflight_cap: int = 0,
                 hedge_min_ms: float = 50.0,
                 hedge_max_ms: float = 2000.0,
                 hedge: bool = True,
                 request_timeout_s: float = 600.0,
                 stream_resume_max: int = 1,
                 stream_journal_size: int = 256,
                 idempotency_window_s: float = 300.0,
                 idempotency_max: int = 1024,
                 registry=None, event_log=None,
                 trace_sample: float = 0.01,
                 trace_slow_ms: float = 1000.0,
                 slo: Optional[dict] = None,
                 alert_windows: str = DEFAULT_ALERT_WINDOWS,
                 alert_for_s: float = 0.0,
                 alert_clear_s: float = 30.0,
                 admin_token: Optional[str] = None,
                 disagg_min_prompt: int = 0):
        self.registry = registry if registry is not None else get_registry()
        self._obs = router_families(self.registry)
        self.event_log = (event_log if event_log is not None
                          else get_event_log())
        # request tracing: the router adopts or mints traceparent at
        # ingress and propagates it on every forward/hedge/stream leg,
        # so one trace id spans the router AND the replica's engine
        # timeline (join via GET /traces on either process)
        self.tracer = TraceRecorder(
            sample=trace_sample, slow_ms=trace_slow_ms,
            counter=self._obs["router_traces_recorded_total"])
        self.replicas = ReplicaSet(replicas, obs=self._obs,
                                   event_log=self.event_log)
        # fleet watchtower: continuous SLO evaluation + burn-rate
        # alerting (router/watchtower.py). Always constructed — the
        # structural replica_down alerts and the /fleetz snapshot ring
        # need no --slo spec; the burn-rate engine activates when one
        # is given. Aggregation rides the prober's on_sweep hook
        # (wired in main(); tests call watchtower.sweep() directly).
        self.watchtower = Watchtower(
            self.replicas, slo=slo, windows=alert_windows,
            for_s=alert_for_s, clear_s=alert_clear_s,
            obs=self._obs, event_log=self.event_log)
        self.admin_token = admin_token or None
        # disaggregated prefill/decode: single-prompt generates at
        # least this many prompt bytes long get a KV-page handoff
        # (prefill replica exports, the chosen decode replica imports)
        # before routing. 0 = off; it also engages only while a
        # prefill-role replica is routable, so mixed fleets see ZERO
        # behavior change either way.
        self.disagg_min_prompt = max(0, int(disagg_min_prompt))
        self.affinity_tokens = int(affinity_tokens)
        self.inflight_cap = int(inflight_cap)
        self.hedge_enabled = bool(hedge)
        self.hedge_min_ms = float(hedge_min_ms)
        self.hedge_max_ms = float(hedge_max_ms)
        self.request_timeout_s = float(request_timeout_s)
        # mid-stream failover state: the per-stream resume journal
        # (bounded ring — every relayed SSE event lands here first, so
        # a replica death can be spliced over and a reconnecting
        # client can replay) and the blocking-generate idempotency
        # window
        self.stream_resume_max = max(0, int(stream_resume_max))
        self.journal = StreamJournal(stream_journal_size, obs=self._obs)
        self.idempotency = IdempotencyCache(
            window_s=idempotency_window_s, max_entries=idempotency_max)
        self.latency = _LatencyWindow()
        self.draining = threading.Event()
        self._http_lock = threading.Lock()
        self._http_inflight = 0
        # per-tenant in-flight accounting: the hedge/spill budget is a
        # shared resource — a tenant already dominating the router's
        # in-flight set must not double its own load with hedges while
        # lighter tenants wait behind the duplicated work
        self._tenant_lock = threading.Lock()
        self._tenant_inflight: dict = {}
        # metric-label cardinality bound: the router has no tenant
        # spec, so client-chosen ids are untrusted — the first 64
        # distinct names get their own label series, the rest fold
        # into "*" (the in-flight ACCOUNTING dict stays exact either
        # way; it self-cleans at request exit)
        self._tenant_label_names: set = set()

    def _tenant_label(self, tenant: str) -> str:
        if (tenant in self._tenant_label_names
                or len(self._tenant_label_names) < 64):
            self._tenant_label_names.add(tenant)
            return tenant
        return "*"

    # -- per-tenant accounting -------------------------------------------

    @staticmethod
    def tenant_of(req: dict, header: Optional[str] = None) -> str:
        """One extraction point, mirroring the replica's: X-Tenant
        header wins, then the body field, then "default"."""
        if header:
            return str(header)
        t = req.get("tenant") if isinstance(req, dict) else None
        return str(t) if t else "default"

    def _tenant_enter(self, tenant: str) -> None:
        with self._tenant_lock:
            n = self._tenant_inflight.get(tenant, 0) + 1
            self._tenant_inflight[tenant] = n
            label = self._tenant_label(tenant)
            if label == "*":  # folded: the series carries the sum of
                #   every beyond-cap tenant, not one tenant's count
                n = sum(v for k, v in self._tenant_inflight.items()
                        if k not in self._tenant_label_names)
        self._obs["router_tenant_inflight"].labels(tenant=label).set(n)

    def _tenant_exit(self, tenant: str) -> None:
        with self._tenant_lock:
            n = max(0, self._tenant_inflight.get(tenant, 0) - 1)
            if n:
                self._tenant_inflight[tenant] = n
            else:
                self._tenant_inflight.pop(tenant, None)
            label = self._tenant_label(tenant)
            if label == "*":
                n = sum(v for k, v in self._tenant_inflight.items()
                        if k not in self._tenant_label_names)
        self._obs["router_tenant_inflight"].labels(tenant=label).set(n)

    def _tenant_may_hedge(self, tenant: str) -> bool:
        """Hedge budget gate: a lone tenant hedges freely (nothing to
        protect — the pre-tenancy behavior), but once several tenants
        are in flight, one holding more than half the router's
        in-flight set (floor 2) has consumed its share — its requests
        run un-hedged so the duplicated work can't squeeze the
        others."""
        with self._tenant_lock:
            mine = self._tenant_inflight.get(tenant, 0)
            total = sum(self._tenant_inflight.values())
        if total - mine <= 0:
            return True
        return mine <= max(2, total // 2)

    def _note_shed(self, rid: str, retry_after: Optional[str],
                   tenant_shed: Optional[str]) -> bool:
        """Fold one 429/503 verdict into replica state. A PER-TENANT
        shed (the replica set ``X-Tenant-Shed``: that tenant is over
        its quota or queue share) is a verdict about the tenant — count
        it, leave the replica fully in rotation, and return True (the
        caller surfaces it without burning the re-route). A global shed
        backs the replica off for its Retry-After as before."""
        if tenant_shed:
            with self._tenant_lock:
                label = self._tenant_label(str(tenant_shed))
            self._obs["router_tenant_sheds_total"].labels(
                tenant=label).inc()
            return True
        self.replicas.note_backoff(rid, parse_retry_after(retry_after))
        return False

    # -- in-flight accounting (drain) ------------------------------------

    def http_enter(self) -> None:
        with self._http_lock:
            self._http_inflight += 1

    def http_exit(self) -> None:
        with self._http_lock:
            self._http_inflight -= 1

    def http_inflight(self) -> int:
        with self._http_lock:
            return self._http_inflight

    # -- admin plane -----------------------------------------------------

    def admin_token_error(self, supplied: Optional[str]):
        """Token gate for the ``/admin/*`` POSTs, the replica's
        taxonomy (train/serve.py) mirrored: 403 while no token is
        configured (fail-closed — the admin plane must be explicitly
        enabled), 401 on a missing/wrong token (constant-time
        compare), ``None`` when authorized."""
        if not self.admin_token:
            return 403, {"error": "admin endpoint disabled "
                                  "(set ROUTER_ADMIN_TOKEN to enable)"}
        if not hmac.compare_digest(str(supplied or ""),
                                   self.admin_token):
            return 401, {"error": "bad or missing X-Admin-Token"}
        return None

    def admin_replicas(self, req: dict) -> Tuple[int, dict]:
        """``POST /admin/replicas`` body ``{"add": [urls], "remove":
        [urls]}`` — runtime membership edits through
        :meth:`ReplicaSet.add`/``remove`` (merge-not-replace: existing
        replicas keep their state/backoff; an added replica starts
        DOWN until the prober admits it and is never pruned by DNS
        absence). This is the autopilot's actuation door AND an
        operator escape hatch."""
        unknown = set(req) - {"add", "remove"}
        if unknown:
            return 400, {"error": f"unknown keys {sorted(unknown)} "
                                  "(want add and/or remove)"}
        add = req.get("add", [])
        remove = req.get("remove", [])
        if not isinstance(add, list) or not isinstance(remove, list):
            return 400, {"error": "add/remove must be URL lists"}
        if not add and not remove:
            return 400, {"error": "body must carry add and/or remove"}
        added = self.replicas.add([str(u) for u in add]) if add else []
        removed = (self.replicas.remove([str(u) for u in remove])
                   if remove else [])
        self.event_log.emit("router_admin_replicas", added=added,
                            removed=removed,
                            replicas=len(self.replicas))
        return 200, {"added": added, "removed": removed,
                     "replicas": self.replicas.snapshot()}

    # -- routing ---------------------------------------------------------

    def _affinity_for(self, req: dict) -> Optional[str]:
        if not self.affinity_tokens:
            return None
        prompts = req.get("prompts")
        prompt = (prompts[0] if isinstance(prompts, list) and prompts
                  else req.get("prompt") or req.get("prefix"))
        if not isinstance(prompt, str) or not prompt:
            return None
        return affinity_key(prompt, self.affinity_tokens)

    @staticmethod
    def _token_ask(req: dict) -> int:
        """Crude token footprint for in-flight scoring: prompt bytes
        (byte tokenizer: bytes == tokens) + the new-token budget."""
        prompts = req.get("prompts") or (
            [req["prompt"]] if isinstance(req.get("prompt"), str) else [])
        try:
            ask = sum(len(p.encode()) for p in prompts
                      if isinstance(p, str))
            ask += int(req.get("max_new_tokens", 64) or 0) * max(
                1, len(prompts))
        except (TypeError, ValueError):
            ask = 64
        return ask

    def pick(self, affinity: Optional[str],
             exclude: Tuple[str, ...] = ()) -> Optional[Replica]:
        routable = self.replicas.routable()
        self._obs["router_replicas_routable"].set(len(routable))
        # role split: ordinary traffic stays off prefill-role replicas
        # while anything else is routable (their step budget belongs
        # to handoff prefills); a fleet degraded to prefill-only still
        # routes — roles are advisory, not a partition of correctness
        pool, _prefill = split_by_role(routable)
        chosen, used_affinity = choose_replica(
            pool, affinity=affinity, inflight_cap=self.inflight_cap,
            exclude=exclude)
        if used_affinity:
            self._obs["router_affinity_hits_total"].inc()
        return chosen

    def maybe_disagg(self, path: str, req: dict, headers=None,
                     span=None) -> Optional[Replica]:
        """Disaggregated prefill/decode handoff: for a long
        single-prompt generate, run the prefill on a prefill-role
        replica (``POST /v1/prefill`` -> base64 KV page blob) and
        install the pages on the decode replica the request will run
        on (``POST /v1/kv_import`` -> radix-trie adoption), so its
        admission is a local cache hit — prefill never steals the
        decode pool's step budget, and TTFT beats the recompute it
        replaces. Returns the warmed decode replica to pin the
        request to, or None for the normal path: disagg off, prompt
        short, no prefill/decode pool, or ANY transfer failure — the
        fallback ladder bottoms out at RECOMPUTE (the replica just
        prefills the prompt itself), never at an error."""
        if not self.disagg_min_prompt or path != "/v1/generate":
            return None
        prompts = req.get("prompts")
        prompt = (prompts[0]
                  if isinstance(prompts, list) and len(prompts) == 1
                  else req.get("prompt"))
        if not isinstance(prompt, str):
            return None
        if (len(prompt.encode("utf-8", "surrogatepass"))
                < self.disagg_min_prompt):
            return None
        routable = self.replicas.routable()
        prefill = pick_prefill(routable)
        decode_pool = [r for r in routable if r.role != "prefill"]
        if prefill is None or not decode_pool:
            return None
        target, _aff = choose_replica(
            decode_pool, affinity=self._affinity_for(req),
            inflight_cap=self.inflight_cap)
        if target is None:
            return None
        tokens = self._token_ask(req)
        t0 = time.perf_counter()
        try:
            status, out, _h = self._finish_call(
                self._forward_once(
                    prefill, "/v1/prefill",
                    json.dumps({"prompt": prompt}).encode(),
                    tokens, headers=headers),
                prefill, tokens)
            if status != 200 or not isinstance(out, dict):
                raise _DisaggFallback(
                    f"prefill export answered {status}")
            blob = out.get("blob")
            if not blob:
                # prompt shorter than one KV page on the replica's
                # bundle shape: nothing transferable, normal path
                self._obs["router_kv_xfer_total"].labels(
                    outcome="export_miss").inc()
                return None
            body = json.dumps({"blob": blob}).encode()
            if len(body) > MAX_BODY_BYTES:
                raise _DisaggFallback(
                    f"page blob ({len(body)} bytes) exceeds the "
                    "replica body cap")
            self._obs["router_kv_xfer_bytes_total"].inc(
                len(blob) * 3 // 4)  # base64 -> raw payload bytes
            status, _out, _h = self._finish_call(
                self._forward_once(target, "/v1/kv_import", body,
                                   tokens, headers=headers),
                target, tokens)
            if status != 200:
                raise _DisaggFallback(f"kv import answered {status}")
        except (ReplicaUnreachable, _DisaggFallback) as exc:
            # transport failures already marked the dead leg DOWN
            # (passive health) inside _forward_once/_finish_call; the
            # request itself falls back to the normal path unharmed
            self._obs["router_kv_xfer_total"].labels(
                outcome="failed").inc()
            self.event_log.emit(
                "router_kv_xfer", outcome="failed",
                prefill=prefill.rid, decode=target.rid,
                error=str(exc)[:200])
            if span is not None:
                span.event("kv_xfer", outcome="failed",
                           error=str(exc)[:200])
            return None
        dt_ms = (time.perf_counter() - t0) * 1000.0
        self._obs["router_kv_xfer_latency_ms"].observe(dt_ms)
        self._obs["router_kv_xfer_total"].labels(outcome="ok").inc()
        if span is not None:
            span.event("kv_xfer", outcome="ok", prefill=prefill.rid,
                       decode=target.rid, ms=round(dt_ms, 1))
        return target

    def hedge_delay_s(self) -> float:
        p99 = self.latency.p99_ms()
        ms = self.hedge_max_ms if p99 is None else min(
            max(p99, self.hedge_min_ms), self.hedge_max_ms)
        return ms / 1000.0

    # -- forwarding ------------------------------------------------------

    def _forward_once(self, replica: Replica, path: str, body: bytes,
                      tokens: int,
                      headers: Optional[dict] = None) -> ReplicaCall:
        """One proxied request; transport failure marks the replica DOWN
        (passive health) and re-raises for the caller's failover.
        ``headers``: extra request headers (the propagated X-Tenant)."""
        self.replicas.track(replica.rid, tokens)
        call = ReplicaCall(replica.base_url,
                           timeout_s=self.request_timeout_s)
        try:
            call.request("POST", path, body=body, headers=headers)
        except ReplicaUnreachable:
            self.replicas.untrack(replica.rid, tokens)
            if not call.cancelled:
                self.replicas.set_state(replica.rid, DOWN,
                                        reason="request transport failure")
            raise
        return call

    def _count(self, replica_rid: str, outcome: str) -> None:
        self._obs["router_requests_total"].labels(
            replica=replica_rid, outcome=outcome).inc()

    def route_json(self, path: str, req: dict,
                   tenant: Optional[str] = None, span=None
                   ) -> Tuple[int, dict, Tuple[Tuple[str, str], ...]]:
        """Route a non-streamed JSON POST end to end. Returns
        (status, body, extra headers) for the HTTP layer. ``tenant``:
        the resolved tenant id (HTTP layer passes the header value);
        falls back to the body field — propagated to the replica as
        X-Tenant and charged against the hedge budget. ``span``: the
        request's trace span — its traceparent rides every leg so the
        replica's engine timeline joins this trace, and the router
        records its route/hedge/reroute decisions as span events."""
        tenant = self.tenant_of(req, tenant)
        body = json.dumps(req).encode()
        affinity = (self._affinity_for(req)
                    if path in ("/v1/generate", "/v1/warm") else None)
        tokens = self._token_ask(req)
        t0 = time.perf_counter()
        tried: List[str] = []
        headers = {"X-Tenant": tenant}
        if span is not None:
            headers["traceparent"] = span.traceparent()

        self._tenant_enter(tenant)
        try:
            # disaggregated handoff first: a long prompt prefills on
            # the prefill pool and the warmed decode replica becomes
            # the pinned primary (its admission is a radix hit); any
            # miss/failure falls through to the normal pick
            primary = self.maybe_disagg(path, req, headers=headers,
                                        span=span)
            if primary is None:
                primary = self.pick(affinity)
            if primary is None:
                if span is not None:
                    span.event("shed", reason="no_replicas")
                self._count("none", "shed")
                self.watchtower.note_request(
                    (time.perf_counter() - t0) * 1000.0, "shed", tenant)
                self.watchtower.note_shed("no_replicas")
                return 503, {"error": "no routable replica",
                             "reason": "no_replicas"}, (
                                 ("Retry-After", "1"),)

            if span is not None:
                span.event("route", replica=primary.rid,
                           affinity=affinity is not None)
            status, out, hdrs, terminal_rid = self._route_with_failover(
                primary, path, body, tokens, tried,
                hedge=(self.hedge_enabled and path == "/v1/generate"
                       and not req.get("stream")
                       and self._tenant_may_hedge(tenant)),
                headers=headers, span=span)
        finally:
            self._tenant_exit(tenant)
        dt_ms = (time.perf_counter() - t0) * 1000.0
        self._obs["router_request_latency_ms"].observe(
            dt_ms, exemplar=(span.trace_id if span is not None else None))
        if 200 <= status < 300:
            self.latency.observe(dt_ms)
            outcome = "ok"
        elif status in (429, 503):
            outcome = "shed"
            self.watchtower.note_shed(
                out.get("reason") if isinstance(out, dict) else None)
        elif status == 502:
            outcome = "unreachable"
        elif 400 <= status < 500:
            outcome = "client_error"
        else:
            outcome = "upstream_error"
        self._count(terminal_rid, outcome)
        self.watchtower.note_request(dt_ms, outcome, tenant)
        return status, out, hdrs

    def route_idempotent(self, idem_key: str, req: dict,
                         tenant: Optional[str] = None, span=None
                         ) -> Tuple[int, dict,
                                    Tuple[Tuple[str, str], ...]]:
        """Non-streamed generate under an ``X-Idempotency-Key``: the
        first request per (tenant, key) executes through
        :meth:`route_json`, concurrent duplicates wait for its verdict,
        and a retry inside the window replays the cached 2xx response
        (marked ``X-Idempotent-Replay: 1``) instead of generating
        twice. Keys are tenant-scoped — one tenant cannot poison or
        read another tenant's cached responses by guessing keys."""
        tenant = self.tenant_of(req, tenant)
        cache_key = f"{tenant}\x00{idem_key}"

        def _run():
            return self.route_json("/v1/generate", req, tenant=tenant,
                                   span=span)

        result, replayed = self.idempotency.execute(
            cache_key, _run, wait_timeout_s=self.request_timeout_s)
        if not replayed:
            return result
        self._obs["router_idempotent_replays_total"].inc()
        if span is not None:
            span.event("idempotent_replay", key=str(idem_key)[:64])
        self.event_log.emit("router_idempotent_replay", tenant=tenant,
                            key=str(idem_key)[:64])
        status, out, hdrs = result
        return status, out, tuple(hdrs) + (("X-Idempotent-Replay", "1"),)

    def _finish_call(self, call: ReplicaCall, replica: Replica,
                     tokens: int) -> Tuple[int, dict,
                                           Tuple[Tuple[str, str], ...]]:
        """Read one completed call's body + relay Retry-After. A death
        mid-body gets the same passive-health verdict as one mid-connect
        (DOWN immediately) — a status line alone proves nothing about a
        live replica — then re-raises for the caller's failover."""
        try:
            status = call.status
            out = call.read_json()
        except ReplicaUnreachable:
            if not call.cancelled:
                self.replicas.set_state(replica.rid, DOWN,
                                        reason="died mid-body")
            raise
        finally:
            self.replicas.untrack(replica.rid, tokens)
            call.close()
        hdrs: Tuple[Tuple[str, str], ...] = ()
        ra = call.header("Retry-After")
        if ra is not None:
            hdrs += (("Retry-After", ra),)
        ts = call.header("X-Tenant-Shed")
        if ts is not None:
            hdrs += (("X-Tenant-Shed", ts),)
        return status, out, hdrs

    def _route_with_failover(self, primary: Replica, path: str,
                             body: bytes, tokens: int, tried: List[str],
                             hedge: bool, headers=None, span=None):
        """primary -> (maybe hedge) -> (maybe one re-route). Returns
        (status, body, headers, terminal_replica_rid)."""
        tried.append(primary.rid)
        try:
            if hedge:
                status, out, hdrs, rid = self._call_hedged(
                    primary, path, body, tokens, tried, headers=headers,
                    span=span)
            else:
                call = self._forward_once(primary, path, body, tokens,
                                          headers=headers)
                status, out, hdrs = self._finish_call(call, primary,
                                                      tokens)
                rid = primary.rid
        except ReplicaUnreachable as exc:
            # transport failure: no status line ever arrived, safe to
            # re-route once (failover)
            self._obs["router_reroutes_total"].labels(
                reason="failover").inc()
            self.event_log.emit("router_reroute", path=path,
                                reason="failover", failed=tried[-1],
                                error=str(exc)[:200])
            if span is not None:
                span.event("reroute", reason="failover",
                           failed=tried[-1])
            return self._reroute_once(path, body, tokens, tried,
                                      shed_status=502,
                                      shed_error=str(exc),
                                      headers=headers, span=span)
        if status in (429, 503):
            hd = dict(hdrs)
            if self._note_shed(rid, hd.get("Retry-After"),
                               hd.get("X-Tenant-Shed")):
                # PER-TENANT shed: the verdict is about the tenant, not
                # the replica — surface it as-is (Retry-After from the
                # tenant's own bucket). No re-route: a tenant over its
                # quota must not consume the spill budget by hopping
                # replicas, and the replica stays fully in rotation
                # for every other tenant.
                return status, out, hdrs, rid
            # global backpressure: the Retry-After backoff landed in
            # _note_shed; ONE re-route to the next best
            self._obs["router_reroutes_total"].labels(
                reason="backpressure").inc()
            self.event_log.emit(
                "router_reroute", path=path, reason="backpressure",
                shed_by=rid,
                retry_after_s=parse_retry_after(hd.get("Retry-After")))
            if span is not None:
                span.event("reroute", reason="backpressure", shed_by=rid)
            return self._reroute_once(path, body, tokens, tried,
                                      shed_status=status,
                                      shed_error=out.get("error", ""),
                                      shed_hdrs=hdrs, headers=headers,
                                      span=span)
        return status, out, hdrs, rid

    def _reroute_once(self, path: str, body: bytes, tokens: int,
                      tried: List[str], *, shed_status: int,
                      shed_error: str, shed_hdrs=(), headers=None,
                      span=None):
        """The single permitted re-route. A second failure — of any
        kind — surfaces to the client; the router never turns one
        request into a retry storm against a struggling fleet."""
        nxt = self.pick(None, exclude=tuple(tried))
        if nxt is None:
            status = shed_status if shed_status in (429, 503) else 502
            return status, {
                "error": f"request failed on {tried[-1]} and no other "
                         f"replica can take it: {shed_error}"[:500],
                "reason": "no_reroute_target",
            }, (tuple(shed_hdrs) or (("Retry-After", "1"),)), tried[-1]
        tried.append(nxt.rid)
        if span is not None:
            span.event("route", replica=nxt.rid, rerouted=True)
        try:
            call = self._forward_once(nxt, path, body, tokens,
                                      headers=headers)
            status, out, hdrs = self._finish_call(call, nxt, tokens)
        except ReplicaUnreachable as exc:
            return 502, {"error": f"re-routed request failed too: "
                                  f"{exc}"[:500],
                         "reason": "reroute_failed"}, (), nxt.rid
        if status in (429, 503):
            # the fallback shed too: its Retry-After is honored (stop
            # offering it work) even though the request now surfaces —
            # the next request must not hammer the same pair. A
            # tenant-scoped shed leaves the fallback in rotation.
            hd = dict(hdrs)
            self._note_shed(nxt.rid, hd.get("Retry-After"),
                            hd.get("X-Tenant-Shed"))
        return status, out, hdrs, nxt.rid

    def _call_hedged(self, primary: Replica, path: str, body: bytes,
                     tokens: int, tried: List[str], headers=None,
                     span=None):
        """Primary + (after the adaptive delay) one hedge; the first
        USABLE response wins and the loser is cancelled (socket close —
        the replica's own deadline machinery reclaims the work). Each
        leg reads its full body before reporting, so a replica that
        sheds 429/503 or dies mid-body cannot "win" the race and get a
        healthy in-flight twin cancelled — the collector waits for the
        outstanding leg and prefers its answer. Leg lifecycle is
        leak-free: error legs untrack themselves; answered legs are
        untracked + closed by the collector (winner and losers alike),
        which consumes every started leg's report before returning.
        Both legs unreachable re-raises :class:`ReplicaUnreachable` so
        the caller's single re-route applies."""
        import queue as _queue

        results: "_queue.Queue" = _queue.Queue()
        lock = threading.Lock()
        calls: List[ReplicaCall] = []
        state = {"committed": False}

        def leg(replica: Replica):
            call = ReplicaCall(replica.base_url,
                               timeout_s=self.request_timeout_s)
            with lock:
                if state["committed"]:
                    # the race was decided before this leg even
                    # registered: abandon without sending (a cancel
                    # loop that ran already could not have seen us)
                    results.put((replica, None, None, None,
                                 ReplicaUnreachable(
                                     "hedge leg abandoned: race "
                                     "already committed")))
                    return
                # registered BEFORE the blocking request so the
                # collector can cancel a leg still on its socket
                calls.append(call)
            self.replicas.track(replica.rid, tokens)
            try:
                call.request("POST", path, body=body, headers=headers)
                status = call.status
                out = call.read_json()
            except ReplicaUnreachable as exc:
                self.replicas.untrack(replica.rid, tokens)
                if not call.cancelled:
                    self.replicas.set_state(
                        replica.rid, DOWN,
                        reason="request transport failure")
                results.put((replica, None, None, None, exc))
                return
            results.put((replica, call, status, out, None))

        threading.Thread(target=leg, args=(primary,),
                         daemon=True).start()
        n_legs = 1
        delay = self.hedge_delay_s()
        try:
            first = results.get(timeout=delay)
        except _queue.Empty:
            first = None
        hedge_rep = None
        if first is None:
            hedge_rep = self.pick(None, exclude=tuple(tried))
            if hedge_rep is not None:
                tried.append(hedge_rep.rid)
                n_legs = 2
                self._obs["router_hedges_total"].inc()
                self.event_log.emit("router_hedge", path=path,
                                    primary=primary.rid,
                                    hedge=hedge_rep.rid,
                                    delay_ms=round(delay * 1000.0, 1))
                if span is not None:
                    span.event("hedge", primary=primary.rid,
                               hedge=hedge_rep.rid,
                               delay_ms=round(delay * 1000.0, 1))
                threading.Thread(target=leg, args=(hedge_rep,),
                                 daemon=True).start()
            first = results.get()  # one leg WILL answer or error

        def usable(r):
            return r[4] is None and r[2] not in (429, 503)

        gathered = [first]
        # a shed or transport error must not beat a leg that may yet
        # answer: wait for the outstanding leg before committing
        while len(gathered) < n_legs and not any(map(usable, gathered)):
            gathered.append(results.get())
        winner = next((r for r in gathered if usable(r)), None)
        won_usable = winner is not None
        if winner is None:
            # no usable answer: a shed verdict (relayable, carries
            # Retry-After) still beats a transport error
            winner = next((r for r in gathered if r[4] is None), None)
        if winner is None:
            raise gathered[-1][4]  # every leg transport-failed
        with lock:
            state["committed"] = True
            for c in calls:
                if c is not winner[1]:
                    c.cancel()
        # loser cleanup happens OFF the response path: every remaining
        # leg report is consumed by a janitor, so the winner's reply is
        # never gated on a loser's socket (an answered loser untracks +
        # closes there; error legs already untracked themselves). A
        # loser that shed still gets its Retry-After honored — losing
        # the race doesn't make the replica less overloaded, and the
        # next request must not route straight back into it.
        losers = [r for r in gathered if r is not winner and r[4] is None]
        outstanding = n_legs - len(gathered)

        def _reap():
            got = list(losers)
            for _ in range(outstanding):
                r = results.get()
                if r[4] is None:
                    got.append(r)
            for r in got:
                if r[2] in (429, 503):
                    # tenant-scoped loser sheds leave the replica in
                    # rotation (the verdict is about the tenant)
                    self._note_shed(
                        r[0].rid, r[1].header("Retry-After"),
                        r[1].header("X-Tenant-Shed"))
                self.replicas.untrack(r[0].rid, tokens)
                r[1].close()

        if losers or outstanding:
            threading.Thread(target=_reap, name="hedge-reap",
                             daemon=True).start()
        replica, call, status, out, _ = winner
        if won_usable and hedge_rep is not None \
                and replica.rid == hedge_rep.rid:
            # only a USABLE hedge answer is a win — a shed verdict that
            # surfaced because every leg shed is a relay, not a rescue
            self._obs["router_hedge_wins_total"].inc()
            if span is not None:
                span.event("hedge_win", replica=replica.rid)
        hdrs: Tuple[Tuple[str, str], ...] = ()
        ra = call.header("Retry-After")
        if ra is not None:
            hdrs += (("Retry-After", ra),)
        ts = call.header("X-Tenant-Shed")
        if ts is not None:
            hdrs += (("X-Tenant-Shed", ts),)  # a surfacing tenant shed
            #   keeps its marker so the failover layer relays, not
            #   re-routes
        self.replicas.untrack(replica.rid, tokens)
        call.close()
        return status, out, hdrs, replica.rid

    # -- streaming -------------------------------------------------------

    def open_stream(self, req: dict, tenant: Optional[str] = None,
                    span=None, exclude: Tuple[str, ...] = ()):
        """Route a streamed generate. Returns ``(replica, call,
        first_lines, tokens)``: for a 200 the stream is PRIMED — the
        response lines up to and including the first ``data:`` event
        are already read into ``first_lines``, so a replica death
        anywhere before the first event (connect refused, died after
        the status line) re-routes here, where nothing has reached the
        client yet. After this returns, the no-replay rule applies: the
        HTTP layer relays and a later death surfaces as a terminal
        error. A 429/503 shed gets the same single re-route as the
        non-streamed path (a shed produced no client-visible bytes, so
        replay is not a concern); if no other replica can take it, the
        FIRST shed verdict is relayed. Other non-200 verdicts return
        unprimed (JSON body, relayed verbatim)."""
        tenant = self.tenant_of(req, tenant)
        body = json.dumps(req).encode()
        tokens = self._token_ask(req)
        affinity = self._affinity_for(req)
        tried: List[str] = []
        headers = {"X-Tenant": tenant}
        if span is not None:
            headers["traceparent"] = span.traceparent()
        # a held shed verdict: still tracked, relayed only if no later
        # attempt produces anything better (_stream untracks + closes)
        shed = None
        tried.extend(exclude)  # a continuation must not re-route back
        #   into the replica whose death it is splicing over
        # disaggregated handoff for long streamed prompts too (TTFT is
        # where the transfer pays most): the warmed decode replica is
        # attempt 0's choice — unless it was already tried (a
        # continuation splice must not land back on the dead replica)
        disagg = (None if tried else self.maybe_disagg(
            "/v1/generate", req, headers=headers, span=span))
        for attempt in range(2):
            replica = ((disagg if attempt == 0 else None)
                       or self.pick(affinity if attempt == 0 else None,
                                    exclude=tuple(tried)))
            if replica is None:
                break
            tried.append(replica.rid)
            if span is not None:
                span.event("route", replica=replica.rid,
                           stream=True, rerouted=attempt > 0)
            try:
                call = self._forward_once(replica, "/v1/generate", body,
                                          tokens, headers=headers)
            except ReplicaUnreachable as exc:
                if span is not None:
                    span.event("reroute", reason="stream_connect",
                               failed=replica.rid)
                self._note_stream_reroute(replica.rid, str(exc))
                continue
            if call.status in (429, 503) and shed is None \
                    and attempt == 0:
                if self._note_shed(replica.rid,
                                   call.header("Retry-After"),
                                   call.header("X-Tenant-Shed")):
                    # per-tenant shed: relay it as-is — no spill to a
                    # second replica (the tenant would double its quota
                    # by hopping), replica stays in rotation
                    return replica, call, [], tokens
                # global backpressure before any bytes reached the
                # client: the backoff landed in _note_shed; try the
                # next-best replica once, like the non-streamed path
                self._obs["router_reroutes_total"].labels(
                    reason="backpressure").inc()
                self.event_log.emit("router_reroute",
                                    path="/v1/generate",
                                    reason="backpressure",
                                    shed_by=replica.rid, stream=True)
                shed = (replica, call)
                continue
            if call.status != 200:
                if call.status in (429, 503):
                    # second-attempt shed (the one permitted re-route
                    # also shed): honored here so the relay layer only
                    # relays
                    self._note_shed(replica.rid,
                                    call.header("Retry-After"),
                                    call.header("X-Tenant-Shed"))
                if shed is not None:
                    self.replicas.untrack(shed[0].rid, tokens)
                    shed[1].close()
                return replica, call, [], tokens
            first_lines: List[bytes] = []
            try:
                for line in call.iter_lines():
                    if not line.endswith(b"\n"):
                        # newline-less = readline hit EOF mid-write:
                        # the replica died writing its first event —
                        # nothing deliverable reached us, so this is
                        # still a death-before-first-event re-route
                        raise ReplicaUnreachable(
                            "stream cut mid-write before the first "
                            "complete event")
                    first_lines.append(line)
                    if line.startswith(b"data:"):
                        break
                else:
                    raise ReplicaUnreachable(
                        "stream ended before the first event")
            except ReplicaUnreachable as exc:
                self.replicas.untrack(replica.rid, tokens)
                call.close()
                self.replicas.set_state(replica.rid, DOWN,
                                        reason="died before first event")
                if span is not None:
                    span.event("reroute", reason="stream",
                               failed=replica.rid)
                self._note_stream_reroute(replica.rid, str(exc))
                continue
            if shed is not None:
                self.replicas.untrack(shed[0].rid, tokens)
                shed[1].close()
            return replica, call, first_lines, tokens
        if shed is not None:
            return shed[0], shed[1], [], tokens
        self._count("none", "shed")
        return None, None, [], tokens

    def _note_stream_reroute(self, rid: str, error: str) -> None:
        self._obs["router_reroutes_total"].labels(reason="stream").inc()
        self.event_log.emit("router_reroute", path="/v1/generate",
                            reason="stream_connect", failed=rid,
                            error=error[:200])

    # -- health ----------------------------------------------------------

    def health(self) -> Tuple[int, dict]:
        routable = len(self.replicas.routable())
        self._obs["router_replicas_routable"].set(routable)
        status = 200 if routable and not self.draining.is_set() else 503
        autoscale = self.replicas.update_autoscale()
        autoscale["replicas_routable"] = routable
        with self._tenant_lock:
            autoscale["demand_inflight"] = sum(
                self._tenant_inflight.values())
            tenants = dict(self._tenant_inflight)
        return status, {
            "status": ("draining" if self.draining.is_set()
                       else "ok" if routable else "no_replicas"),
            "routable": routable,
            "replicas": self.replicas.snapshot(),
            "hedge": {"enabled": self.hedge_enabled,
                      "delay_ms": round(self.hedge_delay_s() * 1000.0, 1)},
            "affinity_tokens": self.affinity_tokens,
            "inflight_cap": self.inflight_cap,
            # the closed-loop capacity signal, in one JSON block an
            # HPA external-metrics adapter (or a human) can read:
            # free headroom vs demand, worst queue delay, and what the
            # Prometheus families expose continuously
            "autoscale": autoscale,
            "tenants_inflight": tenants,
            # watchtower heartbeat: alerts currently firing, in the
            # readiness payload an operator already polls (full detail
            # on GET /alertz)
            "alerts_firing": self.watchtower.alertz()["firing"],
        }


class _SpliceDiverged(RuntimeError):
    """A continuation leg's text did not extend the emitted stream —
    the splice cannot be token-exact, so the stream must end with an
    explicit error terminal instead of silently diverging."""


class _StreamRelay:
    """One client SSE stream relayed across 1 + up-to-``resume_max``
    upstream legs, with every relayed event journaled.

    The relay owns the mid-stream failover contract end to end:

    * every ``data:`` event it writes carries an ``id: <seq>`` line and
      lands in the journal first (payload + parsed token ids + the
      running ``text``);
    * an upstream death after the first event builds a continuation
      request (original prompt + the emitted token IDS, budget reduced
      by the emitted count, the ORIGINAL deadline still enforced from
      first submit) and splices the next replica's stream in — a
      greedy client sees one uninterrupted, token-exact byte run;
    * a CLIENT hang-up detaches the writer but keeps draining the
      still-live upstream into the journal until its terminal, so a
      reconnect (``Last-Event-ID`` + ``X-Request-Id``) replays the
      rest; the outcome counts ``client_disconnect`` regardless of
      which leg was live when the client left, and every leg is
      untracked + closed on every path (leak-free lifecycle).
    """

    def __init__(self, router: RouterServer, handler, req: dict,
                 tenant: Optional[str], span):
        self.router = router
        self.handler = handler
        self.req = req
        self.tenant = tenant
        self.span = span
        self.resume_max = router.stream_resume_max
        self.writer_alive = True
        self.entry = None
        self.resumes = 0
        self.emitted_tokens = 0
        # watchtower timing: stream accept -> first token event is the
        # router-side TTFT; gaps between token events are TBT samples
        self._t0 = time.perf_counter()
        self._last_token_t: Optional[float] = None
        self.leg_validated = True  # first leg needs no splice check
        prompts = req.get("prompts")
        prompt = (prompts[0] if isinstance(prompts, list) and prompts
                  else req.get("prompt"))
        self.orig_prompt = prompt if isinstance(prompt, str) else ""
        try:
            self.orig_budget = int(req.get("max_new_tokens", 64) or 0)
        except (TypeError, ValueError):
            self.orig_budget = 0

    # -- client-side writes ---------------------------------------------

    def _write_raw(self, data: bytes) -> None:
        """Best-effort client write: a dead client socket flips the
        relay into detached mode (journal-only) instead of aborting —
        the upstream leg keeps delivering so a reconnect can replay."""
        if not self.writer_alive:
            return
        try:
            self.handler.wfile.write(data)
            self.handler.wfile.flush()
        except OSError:
            self.writer_alive = False

    def _write_event(self, payload: str, token_ids=(),
                     text: Optional[str] = None) -> None:
        seq = self.router.journal.append(self.entry, payload,
                                         token_ids=token_ids, text=text)
        self._write_raw(f"id: {seq}\ndata: {payload}\n\n".encode())

    # -- relay ----------------------------------------------------------

    def run(self) -> None:
        router, handler = self.router, self.handler
        replica, call, first_lines, tokens = router.open_stream(
            self.req, tenant=self.tenant, span=self.span)
        if call is None:
            return handler._reply(
                503, {"error": "no routable replica for the stream",
                      "reason": "no_replicas"},
                headers=(("Retry-After", "1"),))
        if call.status != 200:
            # replica rejected before streaming (400/429/503): relay
            # its JSON verdict + headers verbatim (shed backoff /
            # tenant accounting already folded in by open_stream)
            try:
                out = call.read_json()
                hdrs: Tuple[Tuple[str, str], ...] = ()
                ra = call.header("Retry-After")
                if ra is not None:
                    hdrs += (("Retry-After", ra),)
                ts = call.header("X-Tenant-Shed")
                if ts is not None:
                    hdrs += (("X-Tenant-Shed", ts),)
                outcome = ("shed" if call.status in (429, 503)
                           else "client_error" if call.status < 500
                           else "upstream_error")
                router._count(replica.rid, outcome)
                if outcome == "shed":
                    router.watchtower.note_shed(
                        out.get("reason") if isinstance(out, dict)
                        else None)
                router.watchtower.note_request(
                    (time.perf_counter() - self._t0) * 1000.0, outcome,
                    router.tenant_of(self.req, self.tenant))
                return handler._reply(call.status, out, headers=hdrs)
            finally:
                router.replicas.untrack(replica.rid, tokens)
                call.close()

        # 200: commit the SSE response and journal the stream. The rid
        # is the journal key AND the client's replay credential — the
        # span's 128-bit trace id, or (span-less direct callers) a
        # fresh uuid; never id()-derived (address reuse would collide
        # journal keys and replay the wrong stream to a reconnect)
        if self.span is not None:
            rid = self.span.trace_id
        else:
            import uuid

            rid = uuid.uuid4().hex
        try:
            handler.close_connection = True
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            handler.send_header("Cache-Control", "no-cache")
            handler.send_header("Connection", "close")
            handler.send_header("X-Request-Id", rid)
            if self.span is not None:
                self.span.set("http.status", 200)
            handler.end_headers()
        except OSError:
            # the CLIENT died between open_stream and the header
            # commit: the tracked upstream leg must still come back
            # (the old _stream's finally discipline)
            router.replicas.untrack(replica.rid, tokens)
            call.cancel()
            router._count(replica.rid, "client_disconnect")
            router.watchtower.note_request(
                (time.perf_counter() - self._t0) * 1000.0,
                "client_disconnect",
                router.tenant_of(self.req, self.tenant))
            return
        self._write_raw(f": trace_id={rid}\n\n".encode())
        deadline_ms = self.req.get("deadline_ms")
        try:
            deadline_s = (float(deadline_ms) / 1000.0
                          if deadline_ms is not None else None)
        except (TypeError, ValueError):
            deadline_s = None
        self.entry = router.journal.open(rid, self.req,
                                         router.tenant_of(self.req,
                                                          self.tenant),
                                         deadline_s=deadline_s)

        upstream_done = False
        last_error = ""
        terminal_rid = replica.rid
        dead_rid = None  # the leg whose death forced the last resume
        while True:
            terminal_rid = replica.rid
            try:
                self._relay_leg(call, first_lines)
                router.replicas.untrack(replica.rid, tokens)
                call.close()
                upstream_done = True
                break
            except _SpliceDiverged as exc:
                # the continuation replica is HEALTHY — its stream just
                # can't be spliced token-exactly; close the leg, no
                # passive-health verdict, and the terminal outcome
                # stays attributed to the DEAD leg that forced the
                # resume (an error-rate dashboard must not blame the
                # healthy replica for a router-side splice mismatch)
                router.replicas.untrack(replica.rid, tokens)
                call.close()
                router._obs["router_stream_resumes_total"].labels(
                    outcome="failed").inc()
                router.watchtower.note_stream_resume("failed")
                last_error = str(exc)
                if dead_rid is not None:
                    terminal_rid = dead_rid
                break
            except ReplicaUnreachable as exc:
                router.replicas.untrack(replica.rid, tokens)
                call.close()
                # passive health with the probe-race shield: the
                # continuation pick below must not see the corpse UP
                router.replicas.note_passive_down(
                    replica.rid, reason="died mid-stream")
                dead_rid = replica.rid
                nxt = self._try_resume(replica.rid, exc)
                if nxt == "completed":
                    upstream_done = True
                    break
                if nxt is None:
                    last_error = str(exc)
                    break
                replica, call, first_lines, tokens = nxt
            except BaseException:
                # safety net: an unexpected relay error must not leak
                # the current leg's in-flight accounting either (the
                # class docstring's every-path promise)
                router.replicas.untrack(replica.rid, tokens)
                call.close()
                raise
        if not upstream_done:
            # the terminal error the client is OWED: tokens already
            # delivered stay delivered, the stream ends with an
            # explicit error event (journaled too — a reconnect must
            # see the same verdict, not a hang)
            self._write_event(json.dumps({"error": last_error or
                                          "stream failed"}))
            self._write_raw(b"data: [DONE]\n\n")
        router.journal.finish(
            self.entry, JOURNAL_DONE if upstream_done else JOURNAL_FAILED)
        if not self.writer_alive:
            outcome = "client_disconnect"
        elif upstream_done:
            outcome = "ok"
        else:
            outcome = "upstream_error"
        if self.span is not None and not self.writer_alive:
            self.span.event("client_disconnect",
                            emitted_tokens=self.emitted_tokens)
        router._count(terminal_rid, outcome)
        router.watchtower.note_request(
            (time.perf_counter() - self._t0) * 1000.0, outcome,
            router.tenant_of(self.req, self.tenant))

    def _relay_leg(self, call: ReplicaCall, first_lines) -> None:
        """Relay one upstream leg to its ``[DONE]``. Raises
        :class:`ReplicaUnreachable` on death (incl. clean EOF without
        the terminator) and :class:`_SpliceDiverged` when a
        continuation fails the token-exactness check."""
        for line in itertools.chain(first_lines, call.iter_lines()):
            if not line.endswith(b"\n"):
                # readline() only returns a newline-less line at
                # EOF/error: the replica died MID-WRITE of this event.
                # The fragment is part of the death, not a deliverable
                # event — relaying it would frame a truncated payload
                # as a complete `data:` line (and journal it for every
                # future replay)
                raise ReplicaUnreachable(
                    "stream cut mid-event (replica died mid-write)")
            payload = sse_payload(line)
            if payload is None:
                continue  # comments / blank separators: the relay
                #   writes its own trace comment + id framing
            if payload == "[DONE]":
                self._write_raw(b"data: [DONE]\n\n")
                return
            self._handle_data(payload)
        raise ReplicaUnreachable(
            "stream ended without [DONE] (replica died mid-stream)")

    def _handle_data(self, payload: str) -> None:
        try:
            ev = json.loads(payload)
        except ValueError:
            ev = None
        if not isinstance(ev, dict):
            self._write_event(payload)
            return
        toks = ev.get("token_ids") or []
        text = ev.get("text")
        if toks and not self.leg_validated:
            # splice sanity, once per continuation leg: the replica
            # frames running text as ORIGINAL prompt + decode(emitted
            # + new), so a leg whose text doesn't even extend the
            # original prompt is not a continuation of this stream
            # (wrong replica build / framing bug) — surface an
            # explicit error instead of splicing garbage
            if (isinstance(text, str) and self.orig_prompt
                    and not text.startswith(self.orig_prompt)):
                raise _SpliceDiverged(
                    "continuation framing does not extend the "
                    "original prompt (not token-exact); surfacing an "
                    "explicit error instead of splicing")
            self.leg_validated = True
        if ev.get("done"):
            # terminal entry: on a spliced stream, normalize the
            # framing to the ORIGINAL request (the continuation-aware
            # replica already frames it; normalizing is idempotent)
            if self.resumes:
                ev["prompt"] = self.orig_prompt
                ev["new_tokens"] = self.emitted_tokens
                ev["resumed"] = True
                ev["resumes"] = self.resumes
                payload = json.dumps(ev)
            self._write_event(payload)
            return
        if toks:
            now = time.perf_counter()
            if self.emitted_tokens == 0:
                self.router.watchtower.note_ttft(
                    (now - self._t0) * 1000.0)
            elif self._last_token_t is not None:
                self.router.watchtower.note_tbt(
                    (now - self._last_token_t) * 1000.0)
            self._last_token_t = now
            self.emitted_tokens += len(toks)
            self._write_event(payload, token_ids=toks,
                              text=text if isinstance(text, str)
                              else None)
            return
        # error terminals (deadline expiry, engine failure) and any
        # future event kinds relay as-is — and are journaled, so a
        # reconnect replays the same verdict
        self._write_event(payload)

    def _try_resume(self, dead_rid: str, exc: Exception):
        """Build + open the continuation leg. Returns the new
        ``(replica, call, first_lines, tokens)``, the string
        ``"completed"`` when the relay synthesized a terminal itself
        (budget already exhausted / deadline expired), or ``None``
        when the stream must end with the error terminal."""
        router = self.router
        res = router._obs["router_stream_resumes_total"]

        def _note(outcome, **extra):
            res.labels(outcome=outcome).inc()
            router.watchtower.note_stream_resume(outcome)
            router.event_log.emit(
                "router_stream_resume", outcome=outcome,
                failed=dead_rid, rid=self.entry.rid,
                emitted_tokens=self.emitted_tokens, **extra)
            if self.span is not None:
                self.span.event("resume", outcome=outcome,
                                failed=dead_rid,
                                emitted_tokens=self.emitted_tokens,
                                **extra)

        if self.resumes >= self.resume_max:
            _note("exhausted")
            return None
        if not self.entry.token_ids or not self.orig_prompt:
            # nothing client-visible was emitted on a leg that still
            # died after open_stream primed it (e.g. the first event
            # was unparseable): no splice point exists
            _note("failed", reason="no_splice_point")
            return None
        remaining_s = self.entry.remaining_deadline_s()
        if remaining_s is not None and remaining_s <= 0:
            # the ORIGINAL deadline (anchored at first submit) is
            # already gone: the verdict is the same one the replica
            # would have delivered
            self.resumes += 1
            self.entry.resumes = self.resumes
            _note("deadline")
            self._write_event(json.dumps({
                "error": "request deadline exceeded before the stream "
                         "could resume"}))
            self._write_raw(b"data: [DONE]\n\n")
            return "completed"
        remaining_budget = self.orig_budget - self.emitted_tokens
        if remaining_budget <= 0:
            # everything but the terminal frame was already delivered:
            # synthesize it from the journal instead of re-generating
            self.resumes += 1
            self.entry.resumes = self.resumes
            _note("ok", synthesized=True)
            self._write_event(json.dumps({
                "prompt": self.orig_prompt,
                "completion": self.entry.last_text,
                "new_tokens": self.emitted_tokens,
                "latency_ms": round(
                    (time.monotonic() - self.entry.created) * 1000.0, 2),
                "done": True, "resumed": True,
                "resumes": self.resumes}))
            self._write_raw(b"data: [DONE]\n\n")
            return "completed"
        cont = dict(self.req)
        cont.pop("prompt", None)
        cont["prompts"] = [self.orig_prompt]
        cont["max_new_tokens"] = remaining_budget
        cont["stream"] = True
        if remaining_s is not None:
            cont["deadline_ms"] = max(1.0, remaining_s * 1000.0)
        # token-id splice point: the replica prefills encode(prompt) +
        # emitted_ids and frames text/counts cumulatively
        # (train/serve.py continuation-aware SSE framing) — ids, not
        # re-tokenized text, so the splice is exact even for byte
        # runs that don't round-trip through UTF-8
        cont["continuation"] = {
            "emitted_ids": list(self.entry.token_ids)}
        self.resumes += 1
        self.entry.resumes = self.resumes
        replica, call, first_lines, tokens = router.open_stream(
            cont, tenant=self.tenant, span=self.span,
            exclude=(dead_rid,))
        if call is None:
            _note("failed", reason="no_target")
            return None
        if call.status != 200:
            router.replicas.untrack(replica.rid, tokens)
            call.close()
            _note("failed", reason=f"http_{call.status}",
                  replica=replica.rid)
            return None
        _note("ok", replica=replica.rid,
              remaining_budget=remaining_budget)
        self.leg_validated = False
        return replica, call, first_lines, tokens


# -- HTTP plumbing -----------------------------------------------------------


def _make_handler(router: RouterServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        _span = None  # the request's trace span (POST paths set it)

        def log_message(self, fmt, *args):
            logger.info("%s %s", self.address_string(), fmt % args)

        def _reply(self, code: int, payload: dict, headers=()):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if self._span is not None:
                # the SAME id the replica echoes — end-to-end join key,
                # present on sheds (429/503) and errors too
                self.send_header("X-Request-Id", self._span.trace_id)
                self._span.set("http.status", code)
            for name, value in headers:
                self.send_header(name, value)
            if self.close_connection:
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            route = self.path.partition("?")[0]
            if route == "/livez":
                # pure liveness: a router with zero routable backends
                # is DEGRADED (readiness /healthz says so), not dead —
                # restarting it revives nothing. Always 200; no
                # replica table read, no lock (the k8s livenessProbe
                # target).
                return self._reply(200, {
                    "live": True,
                    "draining": router.draining.is_set()})
            if route in ("/healthz", "/health", "/"):
                code, payload = router.health()
                return self._reply(code, payload)
            out = handle_obs_request(self.path, router.registry,
                                     router.event_log,
                                     tracer=router.tracer,
                                     watchtower=router.watchtower)
            if out is None:
                return self._reply(404,
                                   {"error": f"unknown path {self.path}"})
            code, ctype, body = out
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _stream(self, req: dict, tenant=None):
            """Relay a replica's SSE stream with journaled, id-framed
            events. A death before the first event fails over inside
            open_stream; a death after it SPLICES a continuation from
            the next-best replica into the same connection
            (``_StreamRelay``); only past --stream-resume-max does the
            explicit error terminal + [DONE] surface. A request
            carrying ``Last-Event-ID`` + ``X-Request-Id`` replays from
            the journal instead of opening a new upstream."""
            last_id = self.headers.get("Last-Event-ID")
            rid = self.headers.get("X-Request-Id")
            if last_id is not None and rid:
                return self._stream_resume(rid, last_id,
                                           router.tenant_of(req, tenant))
            _StreamRelay(router, self, req, tenant, self._span).run()

        def _stream_resume(self, rid: str, last_id: str, tenant: str):
            """Client stream resume: replay journaled events with
            seq > Last-Event-ID, then follow the entry live (the
            original relay keeps draining its upstream after a client
            hang-up) until its terminal state."""
            entry = router.journal.get(rid)
            if entry is not None and entry.tenant != tenant:
                # replay is tenant-scoped like the idempotency window:
                # a stolen/guessed rid from another tenant gets the
                # SAME 404 as an unknown one (existence is information
                # too), never the journaled tokens
                entry = None
            if entry is None:
                return self._reply(
                    404, {"error": f"no journaled stream {rid!r} "
                                   "(finished long ago, evicted, "
                                   "another tenant's, or never seen)",
                          "reason": "resume_unknown"})
            try:
                cursor = int(str(last_id).strip() or "0")
            except ValueError:
                return self._reply(
                    400, {"error": "Last-Event-ID must be the integer "
                                   "seq of the last received event"})
            self.close_connection = True
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            # the ORIGINAL stream's identity, not this connection's —
            # a second blip resumes against the same journal entry
            self.send_header("X-Request-Id", rid)
            self.end_headers()
            replayed_tokens = 0
            from_seq = cursor
            deadline = time.monotonic() + router.request_timeout_s
            try:
                self.wfile.write(f": trace_id={rid}\n\n".encode())
                self.wfile.flush()
                state = JOURNAL_LIVE
                while time.monotonic() < deadline:
                    evs, state = router.journal.wait_events(
                        entry, cursor, timeout_s=5.0)
                    for seq, payload, ntok in evs:
                        self.wfile.write(
                            f"id: {seq}\ndata: {payload}\n\n".encode())
                        self.wfile.flush()
                        cursor = seq
                        replayed_tokens += ntok
                    if not evs and state != JOURNAL_LIVE:
                        break
                if state == JOURNAL_LIVE:
                    # waited out request_timeout with the entry still
                    # live: a truncated replay must NOT masquerade as
                    # a completed stream — surface the cut explicitly
                    # (the client can reconnect again from its cursor)
                    err = json.dumps({
                        "error": "stream replay timed out with the "
                                 "stream still live; reconnect from "
                                 "Last-Event-ID"})
                    self.wfile.write(f"data: {err}\n\n".encode())
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except OSError:
                router._count("journal", "client_disconnect")
                return
            finally:
                if replayed_tokens:
                    router._obs[
                        "router_stream_tokens_replayed_total"].inc(
                            replayed_tokens)
            if self._span is not None:
                self._span.event("stream_replay", rid=rid,
                                 from_seq=from_seq, to_seq=cursor,
                                 tokens=replayed_tokens)
            router._count("journal", "ok")

        def do_POST(self):
            self._span = router.tracer.start_span(
                "router.request",
                parent=self.headers.get("traceparent"),
                attrs={"path": self.path.partition("?")[0]})
            try:
                with use_span(self._span):
                    self._do_post_outer()
            finally:
                self._span.finish()
                # per-connection handler instance: a later GET on the
                # same keep-alive socket must not echo this span's id
                self._span = None

        def _do_post_outer(self):
            if router.draining.is_set():
                self.close_connection = True
                self._span.event("shed", reason="draining")
                return self._reply(
                    503, {"error": "router is draining",
                          "reason": "draining"},
                    headers=(("Retry-After", "5"),))
            router.http_enter()
            try:
                self._do_post_inner()
            finally:
                router.http_exit()

        def _do_post_inner(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                if n > MAX_BODY_BYTES:
                    self.close_connection = True
                    return self._reply(413, {
                        "error": f"body too large ({n} bytes > "
                                 f"{MAX_BODY_BYTES})"})
                req = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, json.JSONDecodeError) as exc:
                return self._reply(400, {"error": f"bad JSON body: {exc}"})
            if self.path == "/admin/replicas":
                # token gate FIRST: an unauthorized caller learns
                # nothing about the body's validity
                err = router.admin_token_error(
                    self.headers.get("X-Admin-Token"))
                if err is not None:
                    return self._reply(*err)
                if not isinstance(req, dict):
                    return self._reply(400, {"error": "body must be a "
                                                      "JSON object"})
                return self._reply(*router.admin_replicas(req))
            if self.path not in ("/v1/generate", "/v1/score", "/v1/warm"):
                return self._reply(404,
                                   {"error": f"unknown path {self.path}"})
            if not isinstance(req, dict):
                return self._reply(400, {"error": "body must be a JSON "
                                                  "object"})
            tenant = router.tenant_of(req, self.headers.get("X-Tenant"))
            try:
                if self.path == "/v1/generate" and req.get("stream"):
                    router._tenant_enter(tenant)
                    try:
                        return self._stream(req, tenant=tenant)
                    finally:
                        router._tenant_exit(tenant)
                idem_key = self.headers.get("X-Idempotency-Key")
                if self.path == "/v1/generate" and idem_key:
                    # dedupe window: a client retry after an ambiguous
                    # 502 replays the cached verdict instead of
                    # generating twice
                    status, out, hdrs = router.route_idempotent(
                        idem_key, req, tenant=tenant, span=self._span)
                else:
                    status, out, hdrs = router.route_json(
                        self.path, req, tenant=tenant, span=self._span)
            except OSError as exc:
                # replica-side transport errors all surface as
                # ReplicaUnreachable, so a raw OSError here is the
                # CLIENT's socket dying mid-write — there is nobody
                # left to reply to (writing a 500 at the dead socket
                # would just double-fault)
                logger.info("client disconnected mid-request: %s", exc)
                return
            except Exception as exc:  # noqa: BLE001 — keep the gateway up
                logger.exception("routing failed")
                status, out, hdrs = 500, {
                    "error": f"{type(exc).__name__}: {exc}"}, ()
            try:
                self._reply(status, out, headers=hdrs)
            except OSError:
                logger.info("client disconnected before the reply")

    return Handler


def start_router_http_server(router: RouterServer, host: str = "0.0.0.0",
                             port: int = 8800) -> ThreadingHTTPServer:
    """Bind and return the router's HTTP server (``port=0`` →
    ephemeral). Caller runs ``serve_forever``."""
    return ThreadingHTTPServer((host, port), _make_handler(router))


# -- CLI ---------------------------------------------------------------------


def parse_args(argv=None) -> argparse.Namespace:
    e = os.environ.get
    p = argparse.ArgumentParser(
        description="Replica-aware router for BundleServer fleets")
    p.add_argument("--replicas", default=e("ROUTER_REPLICAS", ""),
                   help="comma-separated replica base URLs "
                        "(http://host:port,...) — static membership")
    p.add_argument("--discover", default=e("ROUTER_DISCOVER", ""),
                   help="comma-separated DNS name(s) to resolve replicas "
                        "from (k8s headless Service: one A record per "
                        "pod; a disaggregated fleet lists the decode and "
                        "prefill discovery Services); merged with "
                        "--replicas")
    p.add_argument("--discover-port", type=int,
                   default=int(e("ROUTER_DISCOVER_PORT", "8000")),
                   help="replica port for --discover addresses")
    p.add_argument("--host", default=e("ROUTER_HOST", "0.0.0.0"))
    p.add_argument("--port", type=int, default=int(e("ROUTER_PORT", "8800")))
    p.add_argument("--probe-interval", type=float,
                   default=float(e("ROUTER_PROBE_INTERVAL", "1.0")),
                   help="seconds between /loadz health sweeps")
    p.add_argument("--probe-timeout", type=float,
                   default=float(e("ROUTER_PROBE_TIMEOUT", "2.0")))
    p.add_argument("--fail-threshold", type=int,
                   default=int(e("ROUTER_FAIL_THRESHOLD", "2")),
                   help="consecutive probe failures before UP -> DOWN "
                        "(request-path transport failures mark DOWN "
                        "immediately)")
    p.add_argument("--affinity-tokens", type=int,
                   default=int(e("ROUTER_AFFINITY_TOKENS", "32")),
                   help="hash this many leading prompt tokens for "
                        "prefix-affinity routing (0 = pure least-loaded)")
    p.add_argument("--inflight-cap", type=int,
                   default=int(e("ROUTER_INFLIGHT_CAP", "0")),
                   help="per-replica in-flight request cap (0 = none); "
                        "a saturated affinity target spills to the "
                        "least-loaded replica")
    p.add_argument("--disagg-min-prompt", type=int,
                   default=int(e("ROUTER_DISAGG_MIN_PROMPT", "0")),
                   help="disaggregated prefill/decode: prompts at least "
                        "this many bytes long prefill on a prefill-role "
                        "replica and hand the KV pages to the decode "
                        "replica (0 = off; needs a --role prefill "
                        "replica to engage)")
    p.add_argument("--no-hedge", action="store_true",
                   default=e("ROUTER_NO_HEDGE", "") == "1",
                   help="disable hedged failover for non-streamed "
                        "generates")
    p.add_argument("--hedge-min-ms", type=float,
                   default=float(e("ROUTER_HEDGE_MIN_MS", "50")))
    p.add_argument("--hedge-max-ms", type=float,
                   default=float(e("ROUTER_HEDGE_MAX_MS", "2000")))
    p.add_argument("--request-timeout", type=float,
                   default=float(e("ROUTER_REQUEST_TIMEOUT", "600")))
    p.add_argument("--stream-resume-max", type=int,
                   default=int(e("ROUTER_STREAM_RESUME_MAX", "1")),
                   help="mid-stream replica deaths to splice over per "
                        "stream via a continuation request (0 = legacy "
                        "behavior: surface the error terminal); default "
                        "1, consistent with the single re-route")
    p.add_argument("--stream-journal", type=int,
                   default=int(e("ROUTER_STREAM_JOURNAL", "256")),
                   help="bounded stream-resume journal size (entries); "
                        "each relayed stream's events are retained here "
                        "for continuation splicing and Last-Event-ID "
                        "client replay")
    p.add_argument("--idempotency-window", type=float,
                   default=float(e("ROUTER_IDEMPOTENCY_WINDOW", "300")),
                   help="seconds a non-streamed generate's 2xx verdict "
                        "stays replayable under its X-Idempotency-Key "
                        "(bounded to 1024 keys; non-2xx verdicts are "
                        "never cached)")
    p.add_argument("--trace-sample", type=float,
                   default=float(e("ROUTER_TRACE_SAMPLE", "0.01")),
                   help="fraction of routed requests retained in the "
                        "router's /traces flight recorder; traceparent "
                        "ids always propagate to replicas regardless")
    p.add_argument("--trace-slow-ms", type=float,
                   default=float(e("ROUTER_TRACE_SLOW_MS", "1000")),
                   help="always-on slow capture: requests slower than "
                        "this are retained even when unsampled (0=off)")
    p.add_argument("--drain-timeout", type=float,
                   default=float(e("ROUTER_DRAIN_TIMEOUT", "15")),
                   help="seconds SIGTERM waits before stopping the "
                        "accept loop (in-flight proxies finish)")
    p.add_argument("--slo", default=e("ROUTER_SLO", ""),
                   help="live SLO spec for the watchtower's burn-rate "
                        "alerting: inline JSON or @path/to/slo.json, "
                        "the replay/slo.py vocabulary unchanged (e.g. "
                        "'{\"latency_p99_ms\": 2000, \"goodput_min\": "
                        "0.99}'); empty = structural replica_down "
                        "alerts only")
    p.add_argument("--alert-windows",
                   default=e("ROUTER_ALERT_WINDOWS",
                             DEFAULT_ALERT_WINDOWS),
                   help="burn-rate window pairs as short:long:burn "
                        "seconds triples, comma-separated (SRE-workbook "
                        "shape: a fast-burn pair pages quickly, a "
                        "slow-burn pair catches sustained budget spend)")
    p.add_argument("--alert-for", type=float,
                   default=float(e("ROUTER_ALERT_FOR", "0")),
                   help="seconds an alert condition must hold before "
                        "pending -> firing (0 = fire on first "
                        "confirmed evaluation tick)")
    p.add_argument("--alert-clear", type=float,
                   default=float(e("ROUTER_ALERT_CLEAR", "30")),
                   help="seconds of quiet before firing -> resolved "
                        "(hysteresis: flapping input fires once)")
    p.add_argument("--admin-token", default=e("ROUTER_ADMIN_TOKEN", ""),
                   help="shared secret for POST /admin/* (runtime "
                        "replica registration — the autopilot's "
                        "actuation door); empty = admin plane disabled "
                        "(requests get 403)")
    p.add_argument("--autopilot", choices=("off", "recommend"),
                   default=e("ROUTER_AUTOPILOT", "off"),
                   help="closed-loop fleet controller "
                        "(router/autopilot.py): 'recommend' runs the "
                        "decision loop against the in-process "
                        "watchtower and emits autopilot_decision "
                        "events + metrics WITHOUT actuating — the k8s "
                        "HPA stays in charge and operators A/B the "
                        "two before trusting the loop")
    p.add_argument("--autopilot-tick", type=float,
                   default=float(e("ROUTER_AUTOPILOT_TICK", "15")),
                   help="seconds between autopilot decision passes")
    p.add_argument("--autopilot-min", type=int,
                   default=int(e("ROUTER_AUTOPILOT_MIN", "1")),
                   help="autopilot scale rail: never below this many "
                        "replicas")
    p.add_argument("--autopilot-max", type=int,
                   default=int(e("ROUTER_AUTOPILOT_MAX", "8")),
                   help="autopilot scale rail: never above this many "
                        "replicas")
    p.add_argument("--autopilot-stabilization", type=float,
                   default=float(e("ROUTER_AUTOPILOT_STABILIZATION",
                                   "300")),
                   help="seconds desired < up must hold before a "
                        "scale-down is issued (the HPA's "
                        "stabilizationWindowSeconds, mirrored so the "
                        "two controllers never fight)")
    p.add_argument("--autopilot-model",
                   default=e("ROUTER_AUTOPILOT_MODEL", ""),
                   help="calibrated FleetModel JSON for the capacity "
                        "arithmetic: inline JSON or @path (the "
                        "tools/replay.py calibrate dump); empty = "
                        "conservative defaults")
    p.add_argument("--chaos", default=e("ROUTER_CHAOS", ""),
                   help="router-side fault injection over named fault "
                        "points (chaos/inject.py): e.g. "
                        "'router.transport:fail@3' fails the 3rd "
                        "forwarded request, "
                        "'router.probe:fail%%0.2,seed=7' drops each "
                        "health probe w.p. 0.2 (seeded) — exercises "
                        "passive health, failover and probe-flap "
                        "debouncing on their REAL paths; NEVER set in "
                        "production")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if not args.replicas and not args.discover:
        print("router needs --replicas and/or --discover",
              file=sys.stderr)
        return 2
    if args.chaos:
        from pyspark_tf_gke_tpu.chaos.inject import (
            ChaosInjector,
            install as chaos_install,
        )

        injector = ChaosInjector.from_spec(args.chaos)
        if injector is not None:
            chaos_install(injector)
            logger.warning("router chaos injection ACTIVE: %s",
                           injector.describe())
    try:
        slo = parse_slo_spec(args.slo)
    except (ValueError, OSError) as exc:
        print(f"bad --slo spec: {exc}", file=sys.stderr)
        return 2
    replicas = parse_replica_list(args.replicas) if args.replicas else []
    dns_refresh = None
    if args.discover:
        names = [n.strip() for n in args.discover.split(",") if n.strip()]

        def dns_refresh():
            found = []
            for name in names:
                found.extend(
                    resolve_dns_replicas(name, args.discover_port))
            return found

        replicas = replicas + dns_refresh()
    router = RouterServer(
        replicas,
        affinity_tokens=args.affinity_tokens,
        inflight_cap=args.inflight_cap,
        hedge=not args.no_hedge,
        hedge_min_ms=args.hedge_min_ms,
        hedge_max_ms=args.hedge_max_ms,
        request_timeout_s=args.request_timeout,
        stream_resume_max=args.stream_resume_max,
        stream_journal_size=args.stream_journal,
        idempotency_window_s=args.idempotency_window,
        trace_sample=args.trace_sample,
        trace_slow_ms=args.trace_slow_ms,
        slo=slo,
        alert_windows=args.alert_windows,
        alert_for_s=args.alert_for,
        alert_clear_s=args.alert_clear,
        admin_token=args.admin_token,
        disagg_min_prompt=args.disagg_min_prompt)
    autopilot = None
    if args.autopilot != "off":
        from pyspark_tf_gke_tpu.router.autopilot import (
            Autopilot,
            RecommendActuator,
            load_fleet_model,
        )

        try:
            fleet_model = load_fleet_model(args.autopilot_model)
        except (ValueError, OSError) as exc:
            print(f"bad --autopilot-model spec: {exc}", file=sys.stderr)
            return 2
        autopilot = Autopilot(
            fleet_model,
            source=lambda: (router.watchtower.fleetz(n=1),
                            router.watchtower.alertz()),
            actuator=RecommendActuator(event_log=router.event_log),
            min_replicas=args.autopilot_min,
            max_replicas=args.autopilot_max,
            tick_s=args.autopilot_tick,
            stabilization_s=args.autopilot_stabilization,
            registry=router.registry,
            event_log=router.event_log,
            tracer=router.tracer)
    prober = HealthProber(
        router.replicas, interval_s=args.probe_interval,
        timeout_s=args.probe_timeout, fail_threshold=args.fail_threshold,
        dns_refresh=dns_refresh,
        # the watchtower's aggregation + alert tick rides every sweep
        on_sweep=router.watchtower.sweep)
    prober.probe_once()  # first sweep before accepting traffic
    prober.start()
    if autopilot is not None:
        autopilot.start()
        logger.warning("autopilot ACTIVE in %s mode (tick=%.1fs, "
                       "rails=[%d, %d])", args.autopilot,
                       args.autopilot_tick, args.autopilot_min,
                       args.autopilot_max)
    httpd = start_router_http_server(router, args.host, args.port)
    router.event_log.emit("router_started",
                          replicas=[r.rid for r in router.replicas.all()],
                          port=httpd.server_address[1])
    logger.info("routing on http://%s:%d across %d replica(s)",
                *httpd.server_address[:2], len(router.replicas))

    def _drain_then_stop():
        # new POSTs shed 503 the instant draining is set, so the wait
        # only covers proxies already in flight — poll them down and
        # stop early (an idle router drains in one poll interval, not
        # the full --drain-timeout), mirroring BundleServer.drain
        router.draining.set()
        deadline = time.monotonic() + args.drain_timeout
        while time.monotonic() < deadline and router.http_inflight() > 0:
            time.sleep(0.2)
        httpd.shutdown()

    if threading.current_thread() is threading.main_thread():
        import signal

        signal.signal(
            signal.SIGTERM,
            lambda signum, frame: threading.Thread(
                target=_drain_then_stop, name="router-drain",
                daemon=True).start())
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down")
        httpd.shutdown()
    finally:
        if autopilot is not None:
            autopilot.stop()
        prober.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
