"""Fleet watchtower: continuous SLO evaluation + burn-rate alerting.

The replay plane can already say "that run was out of SLO" — after the
run ends (``replay/slo.py``). Nothing in the live path ever said "the
fleet is out of SLO *right now*". This module is that sensor plane,
router-side and stdlib-only like the rest of ``router/``:

* **Fleet snapshot ring** — every :class:`~pyspark_tf_gke_tpu.router
  .discovery.HealthProber` sweep folds the replicas' ``/loadz``
  snapshots (which already carry the ``/stepz`` summary's windowed
  ``step_host_overhead_frac`` + ``step_tokens_per_sec``) into a
  time-bucketed, bounded ring of per-replica records and fleet
  rollups: capacity/demand, worst queue delay, prefix hit + spec
  accept rates, host-overhead max, throughput sum, and the distinct
  ``bundle_generation`` set (a mixed-generation fleet mid-publish is
  one ``/fleetz`` read).
* **Sliding-window SLO evaluation** — the gateway feeds every routed
  request's latency/outcome/tenant, first-event TTFT, inter-token
  gaps, shed reasons and stream-resume verdicts in; the watchtower
  builds an ``evaluate_slo``-shaped report over each window and
  evaluates the UNCHANGED ``replay/slo.py`` vocabulary (``SLO_KEYS``
  is imported, not forked — one SLO language offline and live).
* **Multi-window burn-rate alerting** (Google SRE workbook shape) —
  per-SLO error-budget accounting over short/long window pairs with
  hysteresis and a pending -> firing -> resolved state machine,
  emitting ``router_alert`` events plus the
  ``router_slo_burn_rate{slo,window}`` / ``router_alerts_firing``
  metric families. A structural ``replica_down:<rid>`` alert (always
  on, no SLO spec needed) covers the chaos-native case: a replica
  that was UP and is now DOWN.

Burn-rate semantics, pinned here because tests assert them in closed
form:

* a percentile bound ``latency_p99_ms: B`` budgets ``1 - 0.99`` of
  requests above ``B``; the burn rate over a window is
  ``(fraction of samples > B) / budget`` — 1.0 means "spending the
  budget exactly as fast as allowed", the classic 14.4x/6x fast/slow
  thresholds mean what the SRE workbook says;
* ``goodput_min: G`` budgets ``1 - G`` bad requests (floored at
  ``MIN_BUDGET`` so ``G = 1.0`` stays finite);
* ``tenant_ok_rate_ratio_min: R`` burns ``(1 - ratio) / (1 - R)``;
* count-style keys (``sheds_max`` / ``errors_max`` /
  ``shed_reasons_allowed``) are hard bounds, not budgets: the
  condition is ``value > bound`` in the LONG window while the SHORT
  window still shows activity (so the alert resolves when the burst
  stops), and the exported "burn" is ``value / max(bound, 1)`` for
  dashboard visibility only.

An alert (one per SLO key, plus the structural ones) fires when ANY
configured window pair trips its condition for ``for_s`` consecutive
seconds, and resolves only after ``clear_s`` seconds of quiet —
flapping input produces ONE firing, not a firestorm. Detection bound
for a replica kill: passive health marks DOWN on the first failed
request, so ``<= eval_interval + for_s`` under load; probe-only
detection adds ``fail_threshold x probe_interval + probe_timeout``.

``GET /fleetz`` and ``GET /alertz`` (mounted via
``obs/export.handle_obs_request``) expose all of it with PINNED key
sets — the documented input contract for ROADMAP item 5's autopilot
and the HPA adapter docs.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from pyspark_tf_gke_tpu.replay.slo import SLO_KEYS, evaluate_slo
from pyspark_tf_gke_tpu.replay.stats import summary
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("router.watchtower")

# -- pinned key sets (tests assert these exactly) ----------------------------

# fleet rollup: one dict per ring bucket (and the newest one on /fleetz)
FLEET_ROLLUP_KEYS = (
    "t_s", "wall", "replicas", "up", "draining", "down",
    "capacity_free_total", "demand_tokens_total", "queue_delay_ms_max",
    "step_host_overhead_frac_max", "prefix_hit_rate_mean",
    "spec_accept_rate_mean", "step_tokens_per_sec_total",
    "queued_total", "active_total", "bundle_generations",
    "replica_minutes", "roles",
)

# per-replica record inside a bucket / the /fleetz replicas map
REPLICA_SNAPSHOT_KEYS = (
    "state", "capacity_free", "queue_delay_ms", "prefix_hit_rate",
    "spec_accept_rate", "step_host_overhead_frac", "step_tokens_per_sec",
    "bundle_generation", "queued", "active", "inflight", "role",
)

FLEETZ_KEYS = ("bucket_s", "ring_max", "buckets", "sweeps_total",
               "fleet", "replicas", "history", "cursor")

ALERTZ_KEYS = ("slo", "windows", "for_s", "clear_s", "min_samples",
               "alerts", "firing", "burn_rates", "history", "slo_eval")

ALERT_KEYS = ("name", "kind", "state", "age_s", "value", "fire_count",
              "fired_wall", "resolved_wall")

ALERT_HISTORY_KEYS = ("wall", "age_s", "alert", "from", "to", "value")

# alert states (the state machine's whole vocabulary)
OK = "ok"
PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"

# goodput_min = 1.0 must not divide by zero: the budget floor
MIN_BUDGET = 1e-3

# SLO keys whose violation is a hard count bound, not a burnable budget
_COUNT_KEYS = ("sheds_max", "errors_max", "shed_reasons_allowed")

# gateway outcome -> the replay taxonomy evaluate_slo reads
# (unreachable and upstream_error are both "the fleet failed the
# request"; client_error / client_disconnect are the client's doing and
# excluded from the goodput denominator)
_OUTCOME_CLASS = {
    "ok": "ok",
    "shed": "shed",
    "unreachable": "error",
    "upstream_error": "error",
    "client_error": "client_error",
    "client_disconnect": "client_disconnect",
}
_GOODPUT_OUTCOMES = ("ok", "shed", "error")

DEFAULT_ALERT_WINDOWS = "60:300:10,300:1800:2"


class BurnWindow:
    """One short/long window pair with its burn-rate threshold."""

    __slots__ = ("short_s", "long_s", "burn")

    def __init__(self, short_s: float, long_s: float, burn: float):
        if not (0 < short_s < long_s):
            raise ValueError(
                f"alert window needs 0 < short < long, got "
                f"{short_s}:{long_s}")
        if burn <= 0:
            raise ValueError(f"burn threshold must be > 0, got {burn}")
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.burn = float(burn)

    def as_dict(self) -> dict:
        return {"short_s": self.short_s, "long_s": self.long_s,
                "burn": self.burn}


def parse_alert_windows(spec: str) -> List[BurnWindow]:
    """``"60:300:10,300:1800:2"`` -> window pairs (seconds:seconds:
    burn-threshold). The SRE-workbook defaults pair a fast burn (page
    now) with a slow one (sustained budget spend)."""
    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) != 3:
            raise ValueError(
                f"alert window {part!r} must be short:long:burn")
        out.append(BurnWindow(float(bits[0]), float(bits[1]),
                              float(bits[2])))
    if not out:
        raise ValueError(f"no window pairs in {spec!r}")
    return out


def parse_slo_spec(text: str) -> dict:
    """``--slo`` value -> validated SLO dict: inline JSON or
    ``@path/to/slo.json``. Validation is ``replay/slo.py``'s own
    (unknown keys raise) — the live plane accepts exactly the replay
    vocabulary, nothing forked."""
    text = (text or "").strip()
    if not text:
        return {}
    if text.startswith("@"):
        with open(text[1:]) as fh:
            text = fh.read()
    slo = json.loads(text)
    if not isinstance(slo, dict):
        raise ValueError("--slo must be a JSON object of SLO bounds")
    evaluate_slo({}, slo)  # raises ValueError on unknown keys
    return slo


class Alert:
    """One alert's state-machine record."""

    __slots__ = ("name", "kind", "state", "since_mono", "since_wall",
                 "pending_since", "clear_since", "fired_wall",
                 "resolved_wall", "fire_count", "value")

    def __init__(self, name: str, kind: str, now_mono: float):
        self.name = name
        self.kind = kind  # "slo" | "replica_down"
        self.state = OK
        self.since_mono = now_mono
        self.since_wall = time.time()
        self.pending_since: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.fired_wall: Optional[float] = None
        self.resolved_wall: Optional[float] = None
        self.fire_count = 0
        self.value: Optional[float] = None

    def as_dict(self, now_mono: float) -> dict:
        return {"name": self.name, "kind": self.kind,
                "state": self.state,
                "age_s": round(now_mono - self.since_mono, 3),
                "value": self.value, "fire_count": self.fire_count,
                "fired_wall": self.fired_wall,
                "resolved_wall": self.resolved_wall}


class FleetSnapshotRing:
    """Time-bucketed bounded ring of fleet snapshots. One probe sweep
    folds into the bucket its timestamp lands in (latest sweep in a
    bucket wins — the ring is a downsampled history, not a sweep log),
    so memory is bounded by ``maxlen`` REGARDLESS of probe rate."""

    def __init__(self, bucket_s: float = 2.0, maxlen: int = 256):
        self.bucket_s = max(0.1, float(bucket_s))
        self.maxlen = max(1, int(maxlen))
        self._ring: deque = deque(maxlen=self.maxlen)
        self._lock = threading.Lock()
        self.sweeps_total = 0

    def fold(self, entry: dict, now_mono: float) -> None:
        bucket = int(now_mono / self.bucket_s)
        with self._lock:
            self.sweeps_total += 1
            if self._ring and self._ring[-1][0] == bucket:
                self._ring[-1] = (bucket, entry)
            else:
                self._ring.append((bucket, entry))

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def latest(self) -> Optional[dict]:
        with self._lock:
            return self._ring[-1][1] if self._ring else None

    def history(self, n: Optional[int] = None,
                since: Optional[float] = None) -> List[dict]:
        """Oldest -> newest bucket entries (bounded by ``n``).
        ``since`` is a bucket cursor (bucket start time, seconds in
        the monotonic domain — the ``cursor`` value a previous
        ``/fleetz`` read returned): only entries in STRICTLY newer
        buckets are returned, so a poller re-fetches nothing."""
        with self._lock:
            pairs = list(self._ring)
        if since is not None:
            pairs = [(b, e) for b, e in pairs
                     if b * self.bucket_s > since + 1e-9]
        entries = [e for _, e in pairs]
        return entries[-n:] if n else entries

    def cursor(self) -> Optional[float]:
        """Newest bucket's start time (pass back as ``since=`` to poll
        only deltas); None while the ring is empty."""
        with self._lock:
            if not self._ring:
                return None
            return round(self._ring[-1][0] * self.bucket_s, 3)


class Watchtower:
    """Router-side aggregation + alerting plane (see module doc).

    Thread model: gateway handler threads call the ``note_*`` intake;
    the prober thread calls :meth:`sweep` (which folds the ring and
    runs one :meth:`evaluate` tick); ``/fleetz`` / ``/alertz`` reads
    come from handler threads. One lock, short holds, allocations
    outside it where possible. ``clock`` is injectable so the state
    machine and window math test in closed form."""

    def __init__(self, replicas, *, slo: Optional[dict] = None,
                 windows=DEFAULT_ALERT_WINDOWS,
                 for_s: float = 0.0, clear_s: float = 30.0,
                 min_samples: int = 10,
                 bucket_s: float = 2.0, ring_max: int = 256,
                 max_measurements: int = 8192,
                 obs: Optional[dict] = None, event_log=None,
                 clock: Callable[[], float] = time.monotonic):
        self._replicas = replicas
        self.slo = dict(slo) if slo else {}
        if self.slo:
            evaluate_slo({}, self.slo)  # unknown keys raise, early
        self.windows = (parse_alert_windows(windows)
                        if isinstance(windows, str) else list(windows))
        self.for_s = max(0.0, float(for_s))
        self.clear_s = max(0.0, float(clear_s))
        self.min_samples = max(1, int(min_samples))
        self.ring = FleetSnapshotRing(bucket_s=bucket_s, maxlen=ring_max)
        self._obs = obs
        self._event_log = event_log
        self._clock = clock
        self._lock = threading.Lock()
        horizon = max(w.long_s for w in self.windows)
        self._horizon_s = horizon
        # measurement windows: (t_mono, ...) tuples, newest right;
        # bounded twice — by count (deque maxlen) and by the longest
        # window (pruned on evaluate) — so an idle-then-flooded router
        # can neither grow without bound nor hold stale samples
        m = max(64, int(max_measurements))
        self._requests: deque = deque(maxlen=m)   # (t, ms, class, tenant)
        self._ttft: deque = deque(maxlen=m)       # (t, ms)
        self._tbt: deque = deque(maxlen=m)        # (t, ms)
        self._sheds: deque = deque(maxlen=m)      # (t, reason)
        self._resumes: deque = deque(maxlen=m)    # (t, outcome)
        self._alerts: Dict[str, Alert] = {}
        self._history: deque = deque(maxlen=256)  # transition records
        self._ever_up: set = set()
        # cumulative UP-replica time, in minutes (the autoscaler's cost
        # axis: SLOs held per replica-minute spent). Integrated sweep to
        # sweep, so a 3-replica fleet accrues 3x faster than a 1-replica
        # one; carried on every rollup.
        self._replica_minutes = 0.0
        self._last_sweep_mono: Optional[float] = None
        self._last_burn: Dict[str, Dict[str, float]] = {}
        self._last_slo_eval: Optional[dict] = None

    # -- intake (gateway request path) -----------------------------------

    def note_request(self, latency_ms: float, outcome: str,
                     tenant: str = "default") -> None:
        """One routed request's terminal verdict. ``outcome`` is the
        gateway's taxonomy (``router_requests_total``'s outcome
        label); normalized here to the replay taxonomy."""
        cls = _OUTCOME_CLASS.get(outcome, "error")
        with self._lock:
            self._requests.append((self._clock(), float(latency_ms),
                                   cls, str(tenant)))

    def note_ttft(self, ms: float) -> None:
        """First-event latency of one relayed stream (router-measured:
        stream accept -> first token event written)."""
        with self._lock:
            self._ttft.append((self._clock(), float(ms)))

    def note_tbt(self, ms: float) -> None:
        """Gap between consecutive token events within one stream."""
        with self._lock:
            self._tbt.append((self._clock(), float(ms)))

    def note_shed(self, reason: Optional[str]) -> None:
        """One shed surfaced to a client, by server-reported reason."""
        with self._lock:
            self._sheds.append((self._clock(),
                                str(reason or "unknown")))

    def note_stream_resume(self, outcome: str) -> None:
        """One mid-stream failover attempt's verdict (ok | failed |
        exhausted | deadline — ``router_stream_resumes_total``'s
        vocabulary)."""
        with self._lock:
            self._resumes.append((self._clock(), str(outcome)))

    # -- intake (prober sweep) -------------------------------------------

    def sweep(self) -> dict:
        """Fold one completed probe sweep into the snapshot ring and
        run one alert-evaluation tick. Wired as the prober's
        ``on_sweep`` hook, so aggregation rides the sweep that already
        holds fresh ``/loadz`` bodies — zero extra replica HTTP."""
        now = self._clock()
        reps = self._replicas.all()
        autoscale = self._replicas.update_autoscale()
        per_replica: Dict[str, dict] = {}
        hit_rates, accept_rates, gens = [], [], set()
        tps_total = 0.0
        queued_total = active_total = 0
        counts = {"up": 0, "draining": 0, "down": 0}
        for r in reps:
            load = r.load or {}
            counts[r.state] = counts.get(r.state, 0) + 1
            if r.state == "up":
                self._ever_up.add(r.rid)

            def num(key, default=0.0):
                v = load.get(key)
                return (float(v) if isinstance(v, (int, float))
                        and not isinstance(v, bool) else default)

            tps = num("step_tokens_per_sec")
            rec = {
                "state": r.state,
                "capacity_free": int(num("capacity_free")),
                "queue_delay_ms": num("queue_delay_ms"),
                "prefix_hit_rate": num("prefix_hit_rate"),
                "spec_accept_rate": num("spec_accept_rate"),
                "step_host_overhead_frac": num("step_host_overhead_frac"),
                "step_tokens_per_sec": tps,
                "bundle_generation": load.get("bundle_generation"),
                "queued": int(num("queued")),
                "active": int(num("active")),
                "inflight": r.inflight,
                "role": r.role,
            }
            per_replica[r.rid] = rec
            if r.state == "up":
                hit_rates.append(rec["prefix_hit_rate"])
                accept_rates.append(rec["spec_accept_rate"])
                tps_total += tps
                queued_total += rec["queued"]
                active_total += rec["active"]
            if load.get("bundle_generation") is not None:
                gens.add(load["bundle_generation"])

        def mean(xs):
            return round(sum(xs) / len(xs), 4) if xs else 0.0

        # replica-minutes: rectangle rule over the sweep interval with
        # the CURRENT up count (a replica that died since the last sweep
        # stops accruing at this sweep, not retroactively)
        if self._last_sweep_mono is not None:
            dt = max(0.0, now - self._last_sweep_mono)
            self._replica_minutes += counts.get("up", 0) * dt / 60.0
        self._last_sweep_mono = now

        rollup = {
            "t_s": round(now, 3),
            "wall": round(time.time(), 3),
            "replicas": len(reps),
            "up": counts.get("up", 0),
            "draining": counts.get("draining", 0),
            "down": counts.get("down", 0),
            # the autoscale terms come from ReplicaSet.update_autoscale
            # VERBATIM — the HPA signal and the watchtower can never
            # disagree about capacity math
            "capacity_free_total": autoscale["capacity_free_total"],
            "demand_tokens_total": autoscale["demand_tokens_total"],
            "queue_delay_ms_max": autoscale["queue_delay_ms_max"],
            "step_host_overhead_frac_max":
                autoscale["step_host_overhead_frac_max"],
            "prefix_hit_rate_mean": mean(hit_rates),
            "spec_accept_rate_mean": mean(accept_rates),
            "step_tokens_per_sec_total": round(tps_total, 1),
            "queued_total": queued_total,
            "active_total": active_total,
            "bundle_generations": sorted(gens, key=str),
            "replica_minutes": round(self._replica_minutes, 4),
            # per-role split of the SAME autoscale terms — the HPA for a
            # disaggregated fleet scales prefill and decode Deployments
            # on their own demand/capacity, not the blended totals
            "roles": autoscale.get("by_role", {}),
        }
        entry = {"rollup": rollup, "replicas": per_replica}
        self.ring.fold(entry, now)
        if self._obs is not None:
            c = self._obs.get("router_fleet_snapshots_total")
            if c is not None:
                c.inc()
            g = self._obs.get("router_fleet_snapshot_buckets")
            if g is not None:
                g.set(len(self.ring))
        self.evaluate(now)
        return rollup

    # -- windowed measurement reports ------------------------------------

    def _window_slices(self, window_s: float, now: float):
        cut = now - window_s
        with self._lock:
            reqs = [x for x in self._requests if x[0] >= cut]
            ttft = [ms for t, ms in self._ttft if t >= cut]
            tbt = [ms for t, ms in self._tbt if t >= cut]
            sheds = [r for t, r in self._sheds if t >= cut]
            resumes = [o for t, o in self._resumes if t >= cut]
        return reqs, ttft, tbt, sheds, resumes

    def window_report(self, window_s: float,
                      now: Optional[float] = None) -> dict:
        """``evaluate_slo``-shaped report over the trailing window of
        router-side measurements, plus the router extras (stream
        resumes, raw outcome taxonomy). Same key meanings as the
        replay driver's report — the live and offline SLO verdicts
        speak one language."""
        now = self._clock() if now is None else now
        reqs, ttft, tbt, sheds, resumes = self._window_slices(
            window_s, now)
        outcomes: Dict[str, int] = {}
        shed_reasons: Dict[str, int] = {}
        tenants: Dict[str, List[int]] = {}
        for _, _, cls, tenant in reqs:
            outcomes[cls] = outcomes.get(cls, 0) + 1
            if cls in _GOODPUT_OUTCOMES:
                tot = tenants.setdefault(tenant, [0, 0])
                tot[1] += 1
                if cls == "ok":
                    tot[0] += 1
        for reason in sheds:
            shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
        resume_counts: Dict[str, int] = {}
        for o in resumes:
            resume_counts[o] = resume_counts.get(o, 0) + 1
        counted = sum(outcomes.get(c, 0) for c in _GOODPUT_OUTCOMES)
        goodput = (outcomes.get("ok", 0) / counted if counted else None)
        ratio = None
        rates = [ok / tot for ok, tot in tenants.values() if tot]
        if len(rates) >= 2:
            best = max(rates)
            ratio = round(min(rates) / best, 4) if best > 0 else 0.0
        return {
            "n": len(reqs),
            "window_s": float(window_s),
            "latency_ms": summary([ms for _, ms, _, _ in reqs]),
            "ttft_ms": summary(ttft),
            "tbt_ms": summary(tbt),
            "goodput": (round(goodput, 4)
                        if goodput is not None else None),
            "tenant_ok_rate_ratio": ratio,
            "outcomes": outcomes,
            "sheds": shed_reasons,
            "stream_resumes": resume_counts,
        }

    # -- burn-rate math ---------------------------------------------------

    def _burn_for(self, key: str, bound, window_s: float,
                  now: float) -> Tuple[float, int]:
        """(burn_rate, n_samples) for one SLO key over one window.
        Closed-form (tests pin it): see the module docstring."""
        reqs, ttft, tbt, sheds, _ = self._window_slices(window_s, now)
        if key in ("latency_p50_ms", "latency_p99_ms",
                   "ttft_p50_ms", "ttft_p99_ms",
                   "tbt_p50_ms", "tbt_p99_ms"):
            q = 0.99 if key.endswith("p99_ms") else 0.50
            budget = max(1.0 - q, MIN_BUDGET)
            if key.startswith("latency"):
                xs = [ms for _, ms, _, _ in reqs]
            elif key.startswith("ttft"):
                xs = ttft
            else:
                xs = tbt
            if not xs:
                return 0.0, 0
            bad = sum(1 for v in xs if v > float(bound)) / len(xs)
            return bad / budget, len(xs)
        if key == "goodput_min":
            counted = [x for x in reqs if x[2] in _GOODPUT_OUTCOMES]
            if not counted:
                return 0.0, 0
            budget = max(1.0 - float(bound), MIN_BUDGET)
            bad = 1.0 - (sum(1 for x in counted if x[2] == "ok")
                         / len(counted))
            return bad / budget, len(counted)
        if key == "tenant_ok_rate_ratio_min":
            report = self.window_report(window_s, now)
            ratio = report["tenant_ok_rate_ratio"]
            if ratio is None:
                return 0.0, 0
            budget = max(1.0 - float(bound), MIN_BUDGET)
            return (1.0 - ratio) / budget, report["n"]
        if key == "sheds_max":
            value = sum(1 for x in reqs if x[2] == "shed")
            return value / max(float(bound), 1.0), value
        if key == "errors_max":
            value = sum(1 for x in reqs if x[2] == "error")
            return value / max(float(bound), 1.0), value
        if key == "shed_reasons_allowed":
            allowed = set(bound)
            value = sum(1 for r in sheds if r not in allowed)
            return float(value), value
        return 0.0, 0

    def burn_rates(self, now: Optional[float] = None
                   ) -> Dict[str, Dict[str, float]]:
        """``{slo_key: {"<window>s": burn}}`` over every distinct
        window length in the configured pairs — the
        ``router_slo_burn_rate{slo,window}`` gauge's source."""
        now = self._clock() if now is None else now
        lengths = sorted({w.short_s for w in self.windows}
                         | {w.long_s for w in self.windows})
        out: Dict[str, Dict[str, float]] = {}
        for key, bound in self.slo.items():
            per = {}
            for ws in lengths:
                burn, _ = self._burn_for(key, bound, ws, now)
                per[f"{ws:g}s"] = round(burn, 4)
            out[key] = per
        return out

    def _slo_condition(self, key: str, bound, now: float
                       ) -> Tuple[bool, float]:
        """(condition, worst_burn) across the window pairs."""
        worst = 0.0
        tripped = False
        for w in self.windows:
            b_short, n_short = self._burn_for(key, bound, w.short_s, now)
            b_long, n_long = self._burn_for(key, bound, w.long_s, now)
            worst = max(worst, b_short, b_long)
            if key in _COUNT_KEYS:
                # hard count bound: violated over the long window while
                # the short window still shows activity (resolution
                # when the burst stops)
                if key == "shed_reasons_allowed":
                    if n_long > 0 and n_short > 0:
                        tripped = True
                elif n_long > int(bound) and n_short > 0:
                    tripped = True
            else:
                if (n_short >= self.min_samples
                        and b_short >= w.burn and b_long >= w.burn):
                    tripped = True
        return tripped, worst

    # -- alert state machine ---------------------------------------------

    def _alert(self, name: str, kind: str, now: float) -> Alert:
        a = self._alerts.get(name)
        if a is None:
            a = Alert(name, kind, now)
            self._alerts[name] = a
        return a

    def _transition(self, a: Alert, new_state: str, now: float) -> None:
        prev = a.state
        a.state = new_state
        a.since_mono = now
        a.since_wall = time.time()
        rec = {"wall": round(a.since_wall, 3), "age_s": 0.0,
               "alert": a.name, "from": prev, "to": new_state,
               "value": a.value}
        self._history.append((now, rec))
        if new_state == FIRING:
            a.fire_count += 1
            a.fired_wall = a.since_wall
        if new_state == RESOLVED:
            a.resolved_wall = a.since_wall
        if self._obs is not None:
            g = self._obs.get("router_alerts_firing")
            if g is not None:
                g.labels(alert=a.name).set(1 if new_state == FIRING
                                           else 0)
            c = self._obs.get("router_alert_transitions_total")
            if c is not None:
                c.labels(alert=a.name, state=new_state).inc()
        # event-log policy: firing + resolved only — pending/ok churn
        # under flapping input must not flood the trail (the history
        # ring keeps every transition for /alertz)
        if new_state in (FIRING, RESOLVED) and self._event_log is not None:
            self._event_log.emit("router_alert", alert=a.name,
                                 alert_kind=a.kind, prev=prev,
                                 state=new_state, value=a.value,
                                 fire_count=a.fire_count)
        logger.info("alert %s: %s -> %s (value=%s)", a.name, prev,
                    new_state, a.value)

    def _step_alert(self, a: Alert, condition: bool, value,
                    now: float) -> None:
        """One state-machine tick. pending->firing needs ``for_s`` of
        sustained condition; firing->resolved needs ``clear_s`` of
        quiet (hysteresis: a re-trip during the quiet countdown resets
        it WITHOUT a new firing)."""
        a.value = (round(float(value), 4)
                   if isinstance(value, (int, float)) else value)
        if condition:
            a.clear_since = None
            if a.state in (OK, RESOLVED):
                self._transition(a, PENDING, now)
                a.pending_since = now
            if a.state == PENDING \
                    and now - (a.pending_since or now) >= self.for_s:
                self._transition(a, FIRING, now)
        else:
            if a.state == PENDING:
                a.pending_since = None
                self._transition(a, OK, now)
            elif a.state == FIRING:
                if a.clear_since is None:
                    a.clear_since = now
                if now - a.clear_since >= self.clear_s:
                    a.clear_since = None
                    self._transition(a, RESOLVED, now)

    def evaluate(self, now: Optional[float] = None) -> None:
        """One evaluation tick: burn rates -> gauges, SLO + structural
        alert conditions -> state machines. Called from every probe
        sweep (so cadence = probe interval) and directly by tests."""
        now = self._clock() if now is None else now
        # SLO burn-rate alerts
        if self.slo:
            burns = self.burn_rates(now)
            self._last_burn = burns
            if self._obs is not None:
                g = self._obs.get("router_slo_burn_rate")
                if g is not None:
                    for key, per in burns.items():
                        for win, burn in per.items():
                            g.labels(slo=key, window=win).set(burn)
            for key, bound in self.slo.items():
                cond, worst = self._slo_condition(key, bound, now)
                self._step_alert(self._alert(f"slo:{key}", "slo", now),
                                 cond, worst, now)
            self._last_slo_eval = evaluate_slo(
                self.window_report(self._horizon_s, now), self.slo)
        # structural replica-down alerts: a replica this watchtower has
        # seen UP that is now DOWN is an outage regardless of any SLO
        # spec (DRAINING is intentional and does not trip it)
        for r in self._replicas.all():
            if r.rid not in self._ever_up:
                continue
            a = self._alert(f"replica_down:{r.rid}", "replica_down",
                            now)
            self._step_alert(a, r.state == "down",
                             1.0 if r.state == "down" else 0.0, now)

    # -- endpoint payloads (pinned key sets) ------------------------------

    def fleetz(self, n: int = 32, replica: Optional[str] = None,
               since: Optional[float] = None) -> dict:
        """``GET /fleetz`` body. ``n`` bounds the rollup history;
        ``replica`` substring-filters the per-replica map; ``since``
        (a ``cursor`` from a previous read) restricts ``history`` to
        strictly newer buckets — the autopilot's incremental poll, so
        each tick fetches deltas instead of the whole ring."""
        latest = self.ring.latest() or {"rollup": None, "replicas": {}}
        reps = latest["replicas"]
        if replica:
            reps = {rid: rec for rid, rec in reps.items()
                    if replica in rid}
        return {
            "bucket_s": self.ring.bucket_s,
            "ring_max": self.ring.maxlen,
            "buckets": len(self.ring),
            "sweeps_total": self.ring.sweeps_total,
            "fleet": latest["rollup"],
            "replicas": reps,
            "history": [e["rollup"]
                        for e in self.ring.history(max(1, int(n)),
                                                   since=since)],
            "cursor": self.ring.cursor(),
        }

    def alertz(self, state: Optional[str] = None,
               name: Optional[str] = None, n: int = 64) -> dict:
        """``GET /alertz`` body. ``state`` / ``name`` filter the alert
        list; ``n`` bounds the transition history (newest last)."""
        now = self._clock()
        with self._lock:
            alerts = [a.as_dict(now) for a in self._alerts.values()]
            raw_history = list(self._history)[-max(1, int(n)):]
        # age the history records at read time (their wall stamps are
        # absolute; age_s is a convenience for humans + bench)
        aged = []
        for t_mono, rec in raw_history:
            r = dict(rec)
            r["age_s"] = round(now - t_mono, 3)
            aged.append(r)
        alerts.sort(key=lambda a: a["name"])
        if state:
            alerts = [a for a in alerts if a["state"] == state]
        if name:
            alerts = [a for a in alerts if name in a["name"]]
        return {
            "slo": self.slo,
            "windows": [w.as_dict() for w in self.windows],
            "for_s": self.for_s,
            "clear_s": self.clear_s,
            "min_samples": self.min_samples,
            "alerts": alerts,
            "firing": sorted(a.name for a in self._alerts.values()
                             if a.state == FIRING),
            "burn_rates": self._last_burn,
            "history": aged,
            "slo_eval": self._last_slo_eval,
        }
