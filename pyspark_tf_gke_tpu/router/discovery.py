"""Replica membership + health: who exists, and who can take work.

Membership comes from either a static ``--replicas`` list (tests,
docker-compose, fixed StatefulSets) or a DNS name (`--discover`) that
resolves to one A record per pod — the k8s headless-Service contract
(``infra/k8s/tpu/tpu-router.yaml`` publishes ``tpu-serve-replicas``
with ``clusterIP: None`` exactly so this resolver sees pod IPs, not a
load-balanced VIP that would hide them).

Health is a background :class:`HealthProber` polling each replica's
``GET /loadz`` (one cheap JSON snapshot — queued, queued_tokens, active
slots, kv pages free, draining — so the prober never scrapes Prometheus
text) and folding the answer into one of three states:

* ``UP``        — 200: routable, snapshot fresh;
* ``DRAINING``  — 503 with ``draining`` truthy (PR 3's drain
  semantics): receives NO new work but is NOT dead — its open streams
  finish, so the router must not reset connections to it;
* ``DOWN``      — transport failure / timeout: excluded from routing;
  in-flight requests to it fail over (gateway.py).

The gateway also feeds *passive* health in: a transport failure on a
real request marks the replica DOWN immediately instead of waiting out
a probe interval — that is what makes kill-one-replica failover fast.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from pyspark_tf_gke_tpu.chaos.inject import chaos_fire
from pyspark_tf_gke_tpu.router.client import ReplicaUnreachable, get_json
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("router.discovery")

UP = "up"
DRAINING = "draining"
DOWN = "down"


@dataclass
class Replica:
    """One replica's live routing record. ``load`` is the last /loadz
    snapshot (may be stale by one probe interval — the gateway layers
    its own in-flight accounting on top); ``backoff_until`` implements
    Retry-After honoring: the replica said "not now", so the router
    stops OFFERING it work until the moment passes instead of hammering
    an overloaded pod."""

    rid: str
    base_url: str
    state: str = DOWN
    load: dict = field(default_factory=dict)
    backoff_until: float = 0.0
    consecutive_failures: int = 0
    # True when this replica came from --discover (DNS) rather than the
    # static --replicas list: only discovered replicas are ever pruned
    discovered: bool = False
    # consecutive DNS refreshes that did NOT list this replica — the
    # prune countdown (rolling restarts hand pods new IPs; old ones
    # must not pile up and slow every probe sweep forever)
    dns_absent: int = 0
    # router-side in-flight accounting (gateway increments/decrements):
    # requests and their token footprint currently proxied to this
    # replica — the fresh half of least-outstanding-tokens scoring
    inflight: int = 0
    inflight_tokens: int = 0

    def routable(self, now: Optional[float] = None) -> bool:
        return (self.state == UP
                and (now if now is not None else time.monotonic())
                >= self.backoff_until)

    @property
    def role(self) -> str:
        """Disaggregated serving role as last probed (/loadz ``role``):
        ``prefill`` | ``decode`` | ``mixed``. Replicas that predate the
        key (or haven't answered a probe yet) read as ``mixed`` — the
        role-blind default keeps them fully routable."""
        return str(self.load.get("role") or "mixed")

    def outstanding_tokens(self) -> int:
        """Least-outstanding-tokens score: the replica's own queue
        footprint (from /loadz) plus what this router has in flight to
        it that the snapshot may not see yet."""
        return (int(self.load.get("queued_tokens", 0))
                + int(self.load.get("active", 0))
                + self.inflight_tokens)


def parse_replica_list(spec: str) -> List["Replica"]:
    """``http://a:8000,http://b:8000`` -> replicas keyed by their URL
    (the stable identity label ``router_requests_total{replica=...}``
    uses)."""
    out = []
    for part in spec.split(","):
        part = part.strip().rstrip("/")
        if not part:
            continue
        if "://" not in part:
            part = "http://" + part
        out.append(Replica(rid=part, base_url=part))
    if not out:
        raise ValueError(f"no replicas in spec {spec!r}")
    return out


def resolve_dns_replicas(hostname: str, port: int,
                         resolver: Optional[Callable] = None
                         ) -> List["Replica"]:
    """One A-record per pod (headless Service) -> replica list.
    ``resolver`` is injectable for tests; the default is
    ``socket.getaddrinfo``. Resolution failure returns [] — a router
    must keep serving its last-known membership through a DNS blip,
    so the caller MERGES rather than replaces on empty."""
    import socket

    try:
        infos = (resolver or socket.getaddrinfo)(hostname, port)
    except OSError as exc:
        logger.warning("DNS resolve of %s failed: %s", hostname, exc)
        return []
    seen, out = set(), []
    for info in infos:
        addr = info[4][0]
        if addr in seen:
            continue
        seen.add(addr)
        url = (f"http://[{addr}]:{port}" if ":" in addr
               else f"http://{addr}:{port}")
        out.append(Replica(rid=url, base_url=url, discovered=True))
    return out


class ReplicaSet:
    """Thread-safe replica table. The prober, the DNS refresher, and
    every HTTP handler thread all touch it; one lock, short holds."""

    def __init__(self, replicas: List[Replica], obs=None, event_log=None):
        self._lock = threading.Lock()
        # first-wins on duplicate rids: a URL listed in --replicas AND
        # resolved by --discover must keep its static (never-pruned)
        # record, not be demoted to a prunable discovered one
        self._replicas: Dict[str, Replica] = {}
        for r in replicas:
            self._replicas.setdefault(r.rid, r)
        self._obs = obs
        self._event_log = event_log

    def __len__(self) -> int:
        with self._lock:
            return len(self._replicas)

    def get(self, rid: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(rid)

    def all(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def routable(self) -> List[Replica]:
        now = time.monotonic()
        with self._lock:
            return [r for r in self._replicas.values() if r.routable(now)]

    # DNS refreshes a replica must miss, while already DOWN and with
    # nothing in flight, before it is pruned: rolling restarts retire
    # pod IPs for good, and un-pruned dead entries each cost a probe
    # timeout per sweep forever
    PRUNE_AFTER_ABSENT = 3

    def merge(self, discovered: List[Replica]) -> None:
        """Fold a DNS resolution in: new addresses join (state DOWN
        until the prober confirms them), known ones keep their state.
        A replica that vanished from DNS is NOT removed immediately —
        a DNS blip must not amputate healthy replicas — but one that
        stays absent for ``PRUNE_AFTER_ABSENT`` refreshes AND is DOWN
        AND has nothing in flight is pruned (its pod IP is gone for
        good). Static (``--replicas``) entries are never pruned; an
        empty resolution (resolver failure) changes nothing."""
        if not discovered:
            return
        listed = {r.rid for r in discovered}
        pruned = []
        with self._lock:
            for r in discovered:
                self._replicas.setdefault(r.rid, r)
            for rid, r in list(self._replicas.items()):
                if not r.discovered:
                    continue
                if rid in listed:
                    r.dns_absent = 0
                    continue
                r.dns_absent += 1
                if (r.dns_absent >= self.PRUNE_AFTER_ABSENT
                        and r.state == DOWN and r.inflight == 0):
                    del self._replicas[rid]
                    pruned.append(rid)
        for rid in pruned:
            logger.info("replica %s pruned (absent from DNS)", rid)
            if self._obs is not None:
                self._obs["router_replica_up"].labels(replica=rid).set(0)
            if self._event_log is not None:
                self._event_log.emit("router_replica_state", replica=rid,
                                     prev=DOWN, state="removed",
                                     reason="absent from DNS")

    def add(self, urls: List[str]) -> List[str]:
        """Runtime registration (``POST /admin/replicas`` add, the
        autopilot actuator's path after starting a replica): each URL
        joins as a STATIC entry (never DNS-pruned), state DOWN until a
        probe confirms it — merge-not-replace, so re-adding a known
        URL is a no-op that keeps its live state. Returns the rids
        actually added."""
        added = []
        with self._lock:
            for url in urls:
                url = str(url).strip().rstrip("/")
                if not url:
                    continue
                if "://" not in url:
                    url = "http://" + url
                if url not in self._replicas:
                    self._replicas[url] = Replica(rid=url, base_url=url)
                    added.append(url)
        for rid in added:
            logger.info("replica %s added (admin)", rid)
            if self._obs is not None:
                self._obs["router_replica_up"].labels(replica=rid).set(0)
            if self._event_log is not None:
                self._event_log.emit("router_replica_state", replica=rid,
                                     prev="absent", state=DOWN,
                                     reason="admin add")
        return added

    def remove(self, urls: List[str]) -> List[str]:
        """Runtime deregistration (``POST /admin/replicas`` remove, the
        autopilot's scale-down path BEFORE draining the victim): the
        replica leaves the routing table immediately — its open
        streams finish (the gateway holds its own reference), it just
        gets no new work. Unknown URLs are ignored (idempotent: a
        retried remove must not error). Returns the rids removed."""
        removed = []
        with self._lock:
            for url in urls:
                url = str(url).strip().rstrip("/")
                if url and "://" not in url:
                    url = "http://" + url
                r = self._replicas.pop(url, None)
                if r is not None:
                    removed.append(url)
        for rid in removed:
            logger.info("replica %s removed (admin)", rid)
            if self._obs is not None:
                self._obs["router_replica_up"].labels(replica=rid).set(0)
            if self._event_log is not None:
                self._event_log.emit("router_replica_state", replica=rid,
                                     prev="?", state="removed",
                                     reason="admin remove")
        return removed

    def set_state(self, rid: str, state: str, load: Optional[dict] = None,
                  reason: str = "") -> None:
        """One transition point: metrics gauge + event emit live here so
        the prober and the gateway's passive marking can't diverge."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return
            prev = r.state
            r.state = state
            if load is not None:
                r.load = load
            if state == UP:
                r.consecutive_failures = 0
        if self._obs is not None:
            self._obs["router_replica_up"].labels(replica=rid).set(
                1 if state == UP else 0)
            if load is not None:
                # replica-reported admission-queue delay: one histogram
                # observation per replica per probe sweep — the p99 of
                # this series is the HPA latency signal
                qd = load.get("queue_delay_ms")
                hist = self._obs.get("router_queue_delay_ms")
                if hist is not None and isinstance(qd, (int, float)) \
                        and not isinstance(qd, bool):
                    hist.observe(float(qd))
        if prev != state:
            logger.info("replica %s: %s -> %s%s", rid, prev, state,
                        f" ({reason})" if reason else "")
            if self._event_log is not None:
                self._event_log.emit("router_replica_state", replica=rid,
                                     prev=prev, state=state,
                                     reason=reason[:200])

    def note_passive_down(self, rid: str, reason: str = "",
                          shield_s: float = 1.0) -> None:
        """Passive health with a probe-race shield: mark the replica
        DOWN *and* hold a short backoff so a probe sweep that was
        already in flight (and answered before the death) cannot
        re-admit the corpse for ``shield_s``. The stream-continuation
        path routes its splice IMMEDIATELY after observing the death —
        without the shield, pick() could hand the continuation straight
        back to the replica that just killed the stream. A genuinely
        recovered replica re-admits after the shield via the normal
        first-good-probe rule."""
        self.set_state(rid, DOWN, reason=reason)
        self.note_backoff(rid, shield_s)

    def note_probe_failure(self, rid: str):
        """Count one transport failure; returns (state_before, count)
        so the prober can apply its threshold."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return None, 0
            r.consecutive_failures += 1
            return r.state, r.consecutive_failures

    def note_backoff(self, rid: str, seconds: float) -> None:
        """Honor a Retry-After: stop offering this replica new work for
        ``seconds`` (state stays UP — it answered, it's alive)."""
        until = time.monotonic() + max(0.0, float(seconds))
        with self._lock:
            r = self._replicas.get(rid)
            if r is not None and until > r.backoff_until:
                r.backoff_until = until

    def track(self, rid: str, tokens: int) -> None:
        with self._lock:
            r = self._replicas.get(rid)
            if r is not None:
                r.inflight += 1
                r.inflight_tokens += int(tokens)

    def untrack(self, rid: str, tokens: int) -> None:
        with self._lock:
            r = self._replicas.get(rid)
            if r is not None:
                r.inflight = max(0, r.inflight - 1)
                r.inflight_tokens = max(0,
                                        r.inflight_tokens - int(tokens))

    def update_autoscale(self) -> dict:
        """Fold the fleet's capacity/demand terms into the autoscale
        gauges and return them: ``capacity_free_total`` (sum of UP
        replicas' /loadz token headroom — 0 means saturated, scale up),
        ``demand_tokens_total`` (queued + router-side in-flight tokens
        — the HPA AverageValue numerator), ``queue_delay_ms_max`` (the
        worst replica's last-probed admission delay). Called after
        every probe sweep and from the gateway's /healthz."""
        with self._lock:
            ups = [r for r in self._replicas.values() if r.state == UP]
            cap = sum(int(r.load.get("capacity_free") or 0) for r in ups)
            demand = sum(r.outstanding_tokens() for r in ups)
            delays = [r.load.get("queue_delay_ms") for r in ups]
            fracs = [r.load.get("step_host_overhead_frac") for r in ups]
            # per-role split of the same capacity/demand terms: each
            # role pool scales on its OWN ratio (disaggregated
            # prefill/decode — a saturated prefill pool must not hide
            # behind an idle decode pool's headroom)
            by_role: dict = {}
            for r in ups:
                rec = by_role.setdefault(
                    r.role, {"replicas": 0, "capacity_free_total": 0,
                             "demand_tokens_total": 0})
                rec["replicas"] += 1
                rec["capacity_free_total"] += int(
                    r.load.get("capacity_free") or 0)
                rec["demand_tokens_total"] += r.outstanding_tokens()

        def _max_num(vals):
            return max(
                (float(v) for v in vals
                 if isinstance(v, (int, float))
                 and not isinstance(v, bool)),
                default=0.0)

        delay_max = _max_num(delays)
        # worst routable replica's engine host-overhead share (/loadz
        # step_host_overhead_frac): a fleet whose steps are majority
        # host bookkeeping saturates below its device capacity — the
        # capacity/demand terms alone can't see that
        frac_max = _max_num(fracs)
        if self._obs is not None:
            g = self._obs.get("router_capacity_free_total")
            if g is not None:
                g.set(cap)
            g = self._obs.get("router_demand_tokens_total")
            if g is not None:
                g.set(demand)
            for role, rec in by_role.items():
                for fam, key in (
                        ("router_role_replicas", "replicas"),
                        ("router_role_capacity_free",
                         "capacity_free_total"),
                        ("router_role_demand_tokens",
                         "demand_tokens_total")):
                    g = self._obs.get(fam)
                    if g is not None:
                        g.labels(role=role).set(rec[key])
        return {"capacity_free_total": cap,
                "demand_tokens_total": demand,
                "queue_delay_ms_max": round(delay_max, 2),
                "step_host_overhead_frac_max": round(frac_max, 4),
                "by_role": by_role}

    def snapshot(self) -> List[dict]:
        """JSON-ready table for the router's own /healthz."""
        now = time.monotonic()
        with self._lock:
            return [{
                "replica": r.rid,
                "state": r.state,
                "inflight": r.inflight,
                "inflight_tokens": r.inflight_tokens,
                "backoff_s": round(max(0.0, r.backoff_until - now), 3),
                # rollout visibility: the replica's serving bundle
                # generation as last probed (/loadz) — one router
                # /healthz read shows a mixed-generation fleet mid-
                # publish (None until the first probe answers)
                "bundle_generation": r.load.get("bundle_generation"),
                "load": r.load,
            } for r in sorted(self._replicas.values(),
                              key=lambda x: x.rid)]


class HealthProber:
    """Background thread: every ``interval_s`` poll each replica's
    ``/loadz`` and update the table. ``fail_threshold`` consecutive
    transport failures before UP -> DOWN (one lost packet must not
    flap a healthy replica out of rotation); recovery is immediate
    (first good answer re-admits)."""

    def __init__(self, replicas: ReplicaSet, interval_s: float = 1.0,
                 timeout_s: float = 2.0, fail_threshold: int = 2,
                 dns_refresh: Optional[Callable[[], List[Replica]]] = None,
                 dns_every: int = 10,
                 on_sweep: Optional[Callable[[], None]] = None):
        self.replicas = replicas
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.fail_threshold = max(1, int(fail_threshold))
        self._dns_refresh = dns_refresh
        self._dns_every = max(1, int(dns_every))
        # called once per completed sweep (fresh /loadz in hand) — the
        # watchtower's aggregation + alert-evaluation tick rides here
        # so fleet telemetry costs zero extra replica HTTP
        self._on_sweep = on_sweep
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="router-prober", daemon=True)

    def start(self) -> "HealthProber":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def probe_once(self) -> None:
        """One synchronous sweep (the loop body; tests call it directly
        for determinism). Replicas are probed CONCURRENTLY, so a sweep
        costs ~one probe timeout no matter how many dead entries sit in
        the table — a fleet of unreachable pods probed serially would
        delay a live replica's DRAINING flip by (N x timeout)."""
        reps = self.replicas.all()
        if len(reps) <= 1:
            for r in reps:
                self._probe_one(r)
        else:
            threads = [threading.Thread(
                target=self._probe_one, args=(r,),
                name=f"router-probe-{i}", daemon=True)
                for i, r in enumerate(reps)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=self.timeout_s + 5.0)
        # fold the fresh sweep into the closed-loop autoscale gauges
        self.replicas.update_autoscale()
        if self._on_sweep is not None:
            try:
                self._on_sweep()
            except Exception as exc:  # a sick hook must not kill probing
                logger.warning("on_sweep hook failed: %s", exc)

    def _probe_one(self, r: Replica) -> None:
        try:
            # chaos: the health-probe partition fault point — a fail
            # rule raises ReplicaUnreachable exactly like a probe
            # timing out against a partitioned pod, so fail-threshold
            # debouncing and first-good-probe re-admission run their
            # REAL paths under scheduled (not accidental) timing
            chaos_fire("router.probe", exc=ReplicaUnreachable,
                       replica=r.rid)
            status, body = get_json(r.base_url, "/loadz",
                                    timeout_s=self.timeout_s)
            if status == 404:
                # pre-/loadz replica: degrade to /healthz (strict
                # superset keys are absent but draining/liveness
                # still route correctly)
                status, body = get_json(r.base_url, "/healthz",
                                        timeout_s=self.timeout_s)
        except ReplicaUnreachable as exc:
            was, failures = self.replicas.note_probe_failure(r.rid)
            if was is not None and was != DOWN \
                    and failures >= self.fail_threshold:
                self.replicas.set_state(r.rid, DOWN,
                                        reason=str(exc)[:120])
            return
        except Exception:  # noqa: BLE001 — a probe thread must never
            logger.exception("probe of %s failed", r.rid)  # die silently
            return
        if bool(body.get("draining")) or status == 503:
            self.replicas.set_state(r.rid, DRAINING, load=body,
                                    reason=f"http {status}")
        elif 200 <= status < 300:
            self.replicas.set_state(r.rid, UP, load=body)
        else:
            # answered but unwell (500s): alive enough not to
            # count toward the DOWN threshold, sick enough not to
            # route to — DRAINING's "no new work" is the right bucket
            self.replicas.set_state(r.rid, DRAINING, load=body,
                                    reason=f"http {status}")

    def _loop(self) -> None:
        beat = 0
        while not self._stop.is_set():
            if self._dns_refresh is not None and beat % self._dns_every == 0:
                try:
                    self.replicas.merge(self._dns_refresh())
                except Exception:  # noqa: BLE001 — discovery must not
                    logger.exception("DNS refresh failed")  # kill probing
            beat += 1
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — the prober thread must
                logger.exception("probe sweep failed")  # never die
            self._stop.wait(self.interval_s)
