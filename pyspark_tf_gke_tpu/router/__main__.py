"""CLI entry: ``python -m pyspark_tf_gke_tpu.router --replicas ...``
(what ``infra/k8s/tpu/tpu-router.yaml`` and ``tools/smoke_check.py
--router`` run)."""

import sys

from pyspark_tf_gke_tpu.router.gateway import main

if __name__ == "__main__":
    sys.exit(main())
