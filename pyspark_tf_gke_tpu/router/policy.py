"""Routing policy: least-outstanding-tokens with a prefix-affinity
override.

**Why affinity.** Each replica's slot engine keeps a per-process prefix
cache (``train/continuous.py`` ``prefix_cache_size``): a prompt whose
prefix was prefilled there skips that prefill entirely. The cache is
replica-LOCAL, so a load balancer that sprays same-prefix traffic
uniformly warms N caches to 1/N usefulness each. Hashing the first K
prompt tokens and pinning that hash to one replica (SGLang's
cache-aware routing shape) concentrates the hits.

**Why rendezvous hashing.** ``hash % n`` reshuffles almost every key
when membership changes by one; highest-random-weight (rendezvous)
hashing moves only the keys owned by the lost replica — exactly the
stability a prefix cache wants through a rolling restart.

**Why the override is soft.** Affinity wins only while the target can
absorb the work (UP, not backing off, in-flight below the cap, and not
carrying more than ``spill_ratio`` x the least-loaded replica's
outstanding tokens). Past that, a hot prefix must spill — a cache hit
saved is worth one prefill, not an unbounded queue.

**Why the measured hit rate widens the spill bound.** ``/loadz`` now
reports each replica's ``prefix_hit_rate`` — what its engine-level
radix cache ACTUALLY absorbs, not just hashed ownership. A replica
whose admissions demonstrably hit pays ~the unique-suffix prefill per
request, so the same queue clears faster there: the affinity override
scales its allowance by ``(1 + hit_rate)``, letting a provably-warm
replica hold up to twice the baseline spill threshold before traffic
spills to a cold one (which would re-prefill the whole prefix).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Tuple

from pyspark_tf_gke_tpu.router.discovery import Replica

# Default K: hash this many leading prompt tokens. The platform's
# default byte tokenizer makes bytes == tokens; for other tokenizers
# the prefix of the UTF-8 encoding is a stable proxy (the router has no
# tokenizer on purpose — it must not load a model).
DEFAULT_AFFINITY_TOKENS = 32


def affinity_key(prompt: str, k: int = DEFAULT_AFFINITY_TOKENS) -> str:
    """Stable hash of the first ``k`` prompt tokens (prompt bytes under
    the default byte tokenizer). Same prefix -> same key -> same
    replica -> warm prefix cache."""
    head = prompt.encode("utf-8", "surrogatepass")[:k]
    return hashlib.sha1(head).hexdigest()[:16]


def split_by_role(replicas: List[Replica]
                  ) -> Tuple[List[Replica], List[Replica]]:
    """Partition the routable set for disaggregated serving:
    ``(decode_pool, prefill_pool)``. The decode pool carries ordinary
    generate traffic — ``decode`` and ``mixed`` replicas, plus the
    prefill replicas TOO when nothing else is routable (roles are
    advisory; a fleet degraded to prefill-only must keep serving, just
    without isolation). The prefill pool is ``prefill`` replicas only
    — empty means the handoff path is off and everything rides the
    normal (RECOMPUTE-equivalent) path."""
    prefill = [r for r in replicas if r.role == "prefill"]
    decode = [r for r in replicas if r.role != "prefill"]
    if not decode:
        decode = list(replicas)
    return decode, prefill


def pick_prefill(replicas: List[Replica]) -> Optional[Replica]:
    """Least-outstanding-tokens choice among the PREFILL pool (no
    affinity: prefill replicas are warmed BY the handoff, and the
    radix export is cheap once resident on any of them). None when
    the fleet has no routable prefill replica."""
    _decode, prefill = split_by_role(replicas)
    if not prefill:
        return None
    return min(prefill, key=lambda r: (r.outstanding_tokens(),
                                       r.inflight, r.rid))


def _rendezvous_weight(key: str, rid: str) -> int:
    return int.from_bytes(
        hashlib.sha1(f"{key}|{rid}".encode()).digest()[:8], "big")


def rendezvous_pick(key: str, replicas: List[Replica]) -> Optional[Replica]:
    """Highest-random-weight owner of ``key`` among ``replicas``."""
    if not replicas:
        return None
    return max(replicas, key=lambda r: _rendezvous_weight(key, r.rid))


def choose_replica(replicas: List[Replica], *,
                   affinity: Optional[str] = None,
                   inflight_cap: int = 0,
                   spill_ratio: float = 4.0,
                   exclude: Tuple[str, ...] = ()
                   ) -> Tuple[Optional[Replica], bool]:
    """Pick the replica for one request.

    ``replicas``: the ROUTABLE set (UP, backoff passed — the caller
    filters). ``affinity``: an :func:`affinity_key`, or None for pure
    load balancing. ``inflight_cap``: per-replica in-flight request cap
    (0 = uncapped). ``exclude``: rids already tried (re-route/hedge must
    not land on the same pod twice).

    Returns ``(replica | None, affinity_used)`` — None when nothing can
    take the request (caller sheds 503)."""
    candidates = [r for r in replicas if r.rid not in exclude]
    if not candidates:
        return None, False
    under_cap = [r for r in candidates
                 if not inflight_cap or r.inflight < inflight_cap]
    if not under_cap:
        return None, False
    least = min(under_cap, key=lambda r: (r.outstanding_tokens(),
                                          r.inflight, r.rid))
    if affinity is not None:
        target = rendezvous_pick(affinity, candidates)
        if target is not None and target in under_cap:
            # measured cache effectiveness widens the allowance: a
            # replica whose /loadz hit rate says the prefix cache is
            # absorbing admissions costs ~unique-suffix prefill per
            # request, so it may run up to (1 + hit_rate) x deeper
            # before a spill to a cold replica (full re-prefill) wins
            try:
                hit = min(max(float(
                    target.load.get("prefix_hit_rate") or 0.0), 0.0), 1.0)
            except (TypeError, ValueError):
                hit = 0.0
            allowance = spill_ratio * (1.0 + hit)
            if (target.outstanding_tokens()
                    <= max(allowance * least.outstanding_tokens(),
                           # an idle fleet has score 0 everywhere — the
                           # floor keeps affinity sticky until real load
                           # separates the replicas
                           allowance * 256)):
                return target, True
    return least, False
