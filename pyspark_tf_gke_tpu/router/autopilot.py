"""Autopilot: the closed-loop, chaos-hardened fleet controller.

The watchtower (``router/watchtower.py``) already measures everything
an autoscaler needs — per-sweep fleet rollups (demand tokens, queue
delay, prefix hit rates, bundle generations) and a burn-rate alert
plane — and the capacity model (``replay/capacity.py``) already turns
demand into a replica count. This module closes the loop: a control
thread that reads ``/fleetz`` + ``/alertz`` shaped snapshots, runs
:func:`plan_replicas` over the CALIBRATED model, and actuates scale
decisions through a pluggable :class:`Actuator`.

Robustness is the design center, not an afterthought:

* **Rails** — ``min_replicas``/``max_replicas`` clamp every ask; the
  clamp is visible (a ``rails`` veto) rather than silent.
* **Hysteresis** — scale-down needs ``desired < up`` to hold
  CONTINUOUSLY for ``stabilization_s`` (default 300 s, mirroring the
  HPA's ``stabilizationWindowSeconds`` so the two controllers never
  fight); scale-up is immediate — under-capacity hurts now,
  over-capacity only costs money.
* **Cooldown** — after any applied action the loop holds for
  ``cooldown_s`` so it observes the fleet it just changed before
  changing it again.
* **Do-no-harm vetoes** — scale-down is refused outright while any
  SLO alert is pending/firing (shrinking a burning fleet converts an
  alert into an outage) or while a rollout is mid-publish (mixed
  ``bundle_generations``: eviction would fight the coordinator).
* **Prefix-affinity-aware placement** — scale-down evicts the replica
  whose radix cache is doing the least good (lowest measured
  ``prefix_hit_rate``) and DRAINS it (SIGTERM path: in-flight work
  finishes) instead of killing it; scale-up pre-warms the new replica
  (``/v1/warm``) before registering it so its first routed request
  doesn't pay the cold prefill.
* **Exactly-once actuation** — every actuation attempt passes the
  ``autopilot.actuate`` chaos point and is retried with exponential
  backoff on transient failure; applied work is tracked PER STEP
  (``applied_steps``/``added``) so a retry finishes the remainder and
  an already-applied decision id is never applied twice.
* **Provenance** — every decision carries the rollup snapshot and the
  capacity plan that justified it, emitted as an ``autopilot_decision``
  event and an ``autopilot.tick`` span; a postmortem can replay WHY
  the fleet changed size, not just that it did.

Deployment shapes: in-process on the router (``--autopilot recommend``
— dry-run decisions as events/metrics, the k8s HPA remains the
degraded fallback and operators A/B the two), or driving a
:class:`LocalFleetActuator` in tests/benches where the decisions
actually start and drain replica processes.

Stdlib-only and jax-free, like the rest of the router tier.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

from pyspark_tf_gke_tpu.chaos.inject import chaos_fire
from pyspark_tf_gke_tpu.obs.events import get_event_log
from pyspark_tf_gke_tpu.obs.metrics import autopilot_families
from pyspark_tf_gke_tpu.replay.capacity import FleetModel, plan_replicas
from pyspark_tf_gke_tpu.router.discovery import UP
from pyspark_tf_gke_tpu.router.watchtower import FIRING, PENDING
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("router.autopilot")

# every decision record's key set, in order (tests pin this — the
# provenance contract: docs/AUTOPILOT.md "Decision vocabulary")
DECISION_KEYS = (
    "kind", "id", "t_s", "action", "from", "to", "victim", "added",
    "applied_steps", "applied", "vetoes", "reason", "plan", "rollup",
    "alerts_active",
)

# the veto vocabulary (autopilot_vetoes_total's reason label)
VETO_REASONS = ("alerts_active", "rollout_in_progress", "stabilization",
                "cooldown", "rails", "no_victim")

ACTIONS = ("none", "scale_up", "scale_down")


def load_fleet_model(spec: str = "") -> FleetModel:
    """Build the capacity :class:`FleetModel` from a CLI/env spec:
    empty = the conservative defaults, else inline JSON or ``@path``
    (e.g. a ``calibrate_rates`` dump — keys that aren't FleetModel
    fields, like the dump's measurement metadata, are dropped)."""
    if not spec:
        return FleetModel().validate()
    if spec.startswith("@"):
        with open(spec[1:]) as fh:
            data = json.load(fh)
    else:
        data = json.loads(spec)
    if not isinstance(data, dict):
        raise ValueError("FleetModel spec must be a JSON object")
    fields = {f.name for f in dataclasses.fields(FleetModel)}
    return FleetModel(
        **{k: v for k, v in data.items() if k in fields}).validate()


# -- actuators ---------------------------------------------------------------


class Actuator:
    """The actuation contract. ``scale_up`` provisions + pre-warms +
    registers ONE replica and returns its URL (``None`` when nothing
    concrete was provisioned — the dry-run case); ``scale_down``
    deregisters + drains ``victim`` and returns once it can take no
    new work. Both must tolerate being re-invoked after a mid-flight
    failure (the autopilot retries with per-step tracking)."""

    name = "noop"

    def scale_up(self, decision: dict) -> Optional[str]:
        return None

    def scale_down(self, decision: dict, victim: str) -> bool:
        return True


class RecommendActuator(Actuator):
    """Dry-run actuation: the decision is PUBLISHED (an
    ``autopilot_recommendation`` event per step, and the in-memory
    ``recommendations`` list for tests), never applied. This is the
    k8s shape — the HPA keeps actuating as the degraded fallback
    while operators A/B its moves against the autopilot's."""

    name = "recommend"

    def __init__(self, event_log=None):
        self.event_log = (event_log if event_log is not None
                          else get_event_log())
        self.recommendations: List[dict] = []

    def _emit(self, decision: dict, **extra) -> None:
        rec = {"id": decision["id"], "action": decision["action"],
               "from": decision["from"], "to": decision["to"], **extra}
        self.recommendations.append(rec)
        self.event_log.emit("autopilot_recommendation", **rec)

    def scale_up(self, decision: dict) -> Optional[str]:
        self._emit(decision)
        return None

    def scale_down(self, decision: dict, victim: str) -> bool:
        self._emit(decision, victim=victim)
        return True


def _post_json(url: str, body: dict, headers: Optional[dict] = None,
               timeout_s: float = 60.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read())


class LocalFleetActuator(Actuator):
    """Real actuation against a :class:`router.localfleet.LocalFleet`
    and its router's admin plane — the shape every scale test and
    bench drives.

    Scale-up: boot a fresh replica process, pre-warm it DIRECTLY
    (``/v1/warm`` with the configured hot prefixes — the warm happens
    before registration so the first routed request finds a hot radix
    cache and no cold JIT), then register it with the router (token-
    gated ``POST /admin/replicas``). Scale-down: deregister FIRST (no
    new work routes to it), then SIGTERM-drain; a drain that hangs
    past ``drain_timeout_s`` escalates to SIGKILL — a stuck eviction
    must not wedge the control loop."""

    name = "localfleet"

    def __init__(self, fleet, *, admin_token: str,
                 router_url: Optional[str] = None,
                 warm_prefixes: Sequence[str] = (),
                 drain_timeout_s: float = 30.0,
                 timeout_s: float = 120.0):
        self.fleet = fleet
        self.router_url = (router_url or fleet.url).rstrip("/")
        self.admin_token = admin_token
        self.warm_prefixes = tuple(warm_prefixes)
        self.drain_timeout_s = float(drain_timeout_s)
        self.timeout_s = float(timeout_s)

    def _admin(self, body: dict) -> dict:
        return _post_json(self.router_url + "/admin/replicas", body,
                          headers={"X-Admin-Token": self.admin_token},
                          timeout_s=self.timeout_s)

    def scale_up(self, decision: dict) -> Optional[str]:
        url = self.fleet.start_replica()
        for prefix in (decision.get("warm_prefixes")
                       or self.warm_prefixes):
            try:
                _post_json(url + "/v1/warm", {"prefix": prefix},
                           timeout_s=self.timeout_s)
            except Exception as exc:  # noqa: BLE001 — warm is advisory
                # a failed pre-warm costs one cold prefill, not the
                # scale-up: register the replica anyway
                logger.warning("pre-warm of %s failed: %s", url, exc)
                break
        self._admin({"add": [url]})
        return url

    def scale_down(self, decision: dict, victim: str) -> bool:
        self._admin({"remove": [victim]})
        try:
            i = self.fleet.replica_urls.index(victim)
        except ValueError:
            return True  # already gone: a retried step stays idempotent
        if not self.fleet.drain_replica(i,
                                        timeout_s=self.drain_timeout_s):
            logger.warning("drain of %s hung > %.0fs; escalating to "
                           "SIGKILL", victim, self.drain_timeout_s)
            self.fleet.kill_replica(i)
        return True


# -- the control loop --------------------------------------------------------


class Autopilot:
    """One decision pass per tick: measure -> plan -> guard -> actuate.

    ``source`` is a zero-arg callable returning ``(fleetz, alertz)``
    dicts in the watchtower's wire shapes (in-process:
    ``lambda: (wt.fleetz(n=1), wt.alertz())``; remote: two HTTP GETs).
    Tests drive :meth:`tick` directly with scripted snapshots and an
    injected ``clock``."""

    def __init__(self, model: FleetModel, *,
                 source: Callable[[], Tuple[dict, dict]],
                 actuator: Actuator,
                 min_replicas: int = 1, max_replicas: int = 8,
                 tick_s: float = 15.0,
                 stabilization_s: float = 300.0,
                 cooldown_s: float = 60.0,
                 drain_target_s: float = 5.0,
                 queue_delay_target_ms: float = 500.0,
                 actuate_retries: int = 3,
                 retry_backoff_s: float = 0.5,
                 registry=None, event_log=None, tracer=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Optional[Callable[[float], None]] = None):
        self.model = model.validate()
        self.source = source
        self.actuator = actuator
        if not 1 <= int(min_replicas) <= int(max_replicas):
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.tick_s = max(0.1, float(tick_s))
        self.stabilization_s = max(0.0, float(stabilization_s))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.drain_target_s = float(drain_target_s)
        self.queue_delay_target_ms = float(queue_delay_target_ms)
        self.actuate_retries = max(0, int(actuate_retries))
        self.retry_backoff_s = max(0.0, float(retry_backoff_s))
        self._obs = autopilot_families(registry)
        self.event_log = (event_log if event_log is not None
                          else get_event_log())
        self.tracer = tracer
        self.clock = clock
        self._stop = threading.Event()
        self._sleep = sleep if sleep is not None else self._stop.wait
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._below_since: Optional[float] = None  # hysteresis anchor
        self._last_action_t: Optional[float] = None
        self._applied: set = set()      # decision ids actuated, ever
        self._applied_ring: deque = deque(maxlen=256)
        self.decisions: deque = deque(maxlen=256)  # provenance ring

    # -- decision engine -------------------------------------------------

    @staticmethod
    def _active_alerts(alertz: dict) -> List[str]:
        return [a.get("name", "?") for a in (alertz or {}).get(
            "alerts", []) if a.get("state") in (PENDING, FIRING)]

    @staticmethod
    def _coldest(replicas: dict) -> Optional[str]:
        """Scale-down placement: among UP replicas, the one whose
        radix cache is doing the least good — lowest measured
        ``prefix_hit_rate``, ties broken by least outstanding work
        (its eviction strands the fewest in-flight tokens)."""
        up = [(rid, snap) for rid, snap in (replicas or {}).items()
              if snap.get("state") == UP]
        if not up:
            return None
        return min(up, key=lambda kv: (
            float(kv[1].get("prefix_hit_rate") or 0.0),
            int(kv[1].get("queued") or 0) + int(kv[1].get("active")
                                                or 0)))[0]

    def decide(self, fleetz: dict, alertz: dict) -> dict:
        """One closed-form decision over one snapshot pair. Pure with
        respect to the FLEET (no actuation) but it advances the
        hysteresis clock — call once per tick."""
        now = self.clock()
        rollup = (fleetz or {}).get("fleet") or {}
        replicas = (fleetz or {}).get("replicas") or {}
        up = int(rollup.get("up") or 0)
        plan = plan_replicas(
            self.model,
            demand_tokens=float(rollup.get("demand_tokens_total")
                                or 0.0),
            queue_delay_ms=rollup.get("queue_delay_ms_max"),
            replicas_up=up,
            min_replicas=self.min_replicas,
            max_replicas=self.max_replicas,
            drain_target_s=self.drain_target_s,
            queue_delay_target_ms=self.queue_delay_target_ms)
        desired = plan["replicas_needed"]
        self._obs["autopilot_replicas_desired"].set(desired)

        # hysteresis anchor: when did desired first drop below up and
        # STAY there? Any tick at/above up resets the window.
        if desired < up:
            if self._below_since is None:
                self._below_since = now
        else:
            self._below_since = None

        active = self._active_alerts(alertz)
        gens = rollup.get("bundle_generations") or []
        in_cooldown = (self._last_action_t is not None
                       and now - self._last_action_t < self.cooldown_s)

        action, victim, target = "none", None, up
        vetoes: List[str] = []
        reason = (f"demand {plan['demand_tokens']} tok / queue delay "
                  f"{plan['queue_delay_ms']} ms -> {desired} replicas "
                  f"(up: {up})")
        if desired > up:
            if in_cooldown:
                vetoes.append("cooldown")
            else:
                action, target = "scale_up", desired
        elif desired < up:
            # do-no-harm gauntlet, every blocked guard recorded (a
            # scale-down that waited on 3 guards shows all 3)
            if active:
                vetoes.append("alerts_active")
            if len(gens) > 1:
                vetoes.append("rollout_in_progress")
            if self._below_since is None or \
                    now - self._below_since < self.stabilization_s:
                vetoes.append("stabilization")
            if in_cooldown:
                vetoes.append("cooldown")
            if not vetoes:
                victim = self._coldest(replicas)
                if victim is None:
                    vetoes.append("no_victim")
                else:
                    # one replica per decision: eviction is the risky
                    # direction, so converge in observed steps
                    action, target = "scale_down", up - 1
        elif plan["replicas_unclamped"] != desired:
            # the rails absorbed the whole ask (e.g. demand wants 12,
            # max is 8, fleet is at 8): visible, not silent
            vetoes.append("rails")

        self._seq += 1
        return {
            "kind": "autopilot_decision",
            "id": f"d{self._seq}",
            "t_s": round(now, 3),
            "action": action,
            "from": up,
            "to": target,
            "victim": victim,
            "added": [],
            "applied_steps": 0,
            "applied": False,
            "vetoes": vetoes,
            "reason": reason,
            "plan": plan,
            "rollup": rollup,
            "alerts_active": active,
        }

    # -- actuation (retry + exactly-once) --------------------------------

    def _apply(self, decision: dict) -> None:
        """One actuation attempt. Progress is tracked per STEP inside
        the decision (``applied_steps``/``added``), so an attempt that
        fails midway leaves a resumable record — the retry finishes
        the remainder instead of re-running completed steps."""
        action = decision["action"]
        if action == "scale_up":
            want = decision["to"] - decision["from"]
            while decision["applied_steps"] < want:
                chaos_fire("autopilot.actuate", action=action,
                           decision_id=decision["id"],
                           step=decision["applied_steps"])
                url = self.actuator.scale_up(decision)
                decision["applied_steps"] += 1
                if url:
                    decision["added"].append(url)
        elif action == "scale_down":
            if decision["applied_steps"] < 1:
                chaos_fire("autopilot.actuate", action=action,
                           decision_id=decision["id"], step=0)
                self.actuator.scale_down(decision, decision["victim"])
                decision["applied_steps"] = 1

    def _actuate(self, decision: dict) -> bool:
        """Apply one decision exactly once, retrying transient
        actuator failures with exponential backoff. Exhausting the
        retries DROPS the decision (counted + evented) — the next
        tick re-measures and re-decides against the fleet's actual
        state, which beats blindly re-driving a stale plan."""
        if decision["id"] in self._applied:
            return True  # never double-apply (replayed tick/decision)
        action, attempts = decision["action"], 0
        while True:
            try:
                self._apply(decision)
            except Exception as exc:  # noqa: BLE001 — actuators raise
                #   anything (subprocess, urllib, chaos)
                attempts += 1
                if attempts > self.actuate_retries:
                    self._obs["autopilot_actuations_total"].labels(
                        action=action, outcome="failed").inc()
                    self.event_log.emit(
                        "autopilot_actuation_failed", id=decision["id"],
                        action=action, attempts=attempts,
                        error=str(exc)[:200])
                    logger.warning("actuation %s (%s) failed after %d "
                                   "attempts: %s", decision["id"],
                                   action, attempts, exc)
                    return False
                self._obs["autopilot_actuation_retries_total"].inc()
                self.event_log.emit(
                    "autopilot_actuation_retry", id=decision["id"],
                    action=action, attempt=attempts,
                    error=str(exc)[:200])
                self._sleep(self.retry_backoff_s * (2 ** (attempts - 1)))
                continue
            if len(self._applied_ring) == self._applied_ring.maxlen:
                self._applied.discard(self._applied_ring[0])
            self._applied_ring.append(decision["id"])
            self._applied.add(decision["id"])
            self._obs["autopilot_actuations_total"].labels(
                action=action, outcome="ok").inc()
            return True

    # -- the tick --------------------------------------------------------

    def tick(self) -> dict:
        """One measure -> plan -> guard -> actuate pass. Always
        returns the decision record (no-ops included); the record is
        also kept in the bounded ``decisions`` ring."""
        span = (self.tracer.start_span("autopilot.tick")
                if self.tracer is not None else None)
        try:
            fleetz, alertz = self.source()
            decision = self.decide(fleetz, alertz)
            self._obs["autopilot_ticks_total"].inc()
            self._obs["autopilot_decisions_total"].labels(
                action=decision["action"]).inc()
            for veto in decision["vetoes"]:
                self._obs["autopilot_vetoes_total"].labels(
                    reason=veto).inc()
            if span is not None:
                span.event("decision", id=decision["id"],
                           action=decision["action"],
                           replicas_from=decision["from"],
                           to=decision["to"],
                           vetoes=decision["vetoes"],
                           desired=decision["plan"]["replicas_needed"])
            if decision["action"] != "none" or decision["vetoes"]:
                # full provenance on anything non-trivial: the rollup
                # + plan that justified (or blocked) the move ride the
                # event, so the trail alone reconstructs the WHY
                self.event_log.emit("autopilot_decision", **{
                    k: decision[k] for k in DECISION_KEYS
                    if k not in ("kind",)})
            if decision["action"] != "none":
                decision["applied"] = self._actuate(decision)
                if decision["applied"]:
                    self._last_action_t = self.clock()
                    self._below_since = None
                    logger.info(
                        "autopilot %s: %s %d -> %d%s", decision["id"],
                        decision["action"], decision["from"],
                        decision["to"],
                        f" (victim {decision['victim']})"
                        if decision["victim"] else "")
                    if span is not None:
                        span.event("actuated", id=decision["id"],
                                   added=decision["added"],
                                   victim=decision["victim"])
            self.decisions.append(decision)
            return decision
        finally:
            if span is not None:
                span.finish()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Autopilot":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the loop survives
                    #   a torn snapshot or a dead source; next tick
                    #   re-reads
                    logger.exception("autopilot tick failed")
                self._stop.wait(self.tick_s)

        self._thread = threading.Thread(target=loop, name="autopilot",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
