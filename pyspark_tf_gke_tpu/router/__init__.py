"""Replica-aware serving router: the data-plane gateway in front of N
``BundleServer`` replicas.

The source platform's whole point is a *routed* system — a coordinator
submits work to a master that fans out across workers. PRs 2–4 made one
serving replica fast (paged KV, chunked prefill) and survivable
(deadlines, drain, chaos); this package is the tier that spreads traffic
across N of them:

* :mod:`discovery`   — membership (static list / DNS headless Service)
  + a background prober tracking UP / DRAINING / DOWN per replica from
  its ``/loadz`` snapshot;
* :mod:`policy`      — least-outstanding-tokens scoring with a
  prefix-affinity override (same-prefix traffic lands on the replica
  whose engine prefix cache is already warm);
* :mod:`client`      — thin cancellable HTTP client + the ONE
  ``Retry-After`` parser both the forwarding path and the prober use;
* :mod:`gateway`     — the HTTP server: backpressure propagation
  (honor ``Retry-After``, re-route once, never amplify retries into an
  overloaded pod), hedged failover for non-streamed generates, and
  mid-stream failover (token-exact continuation splicing over a
  replica death, ``Last-Event-ID`` client replay, ``X-Idempotency-Key``
  dedupe);
* :mod:`journal`     — the bounded per-stream resume journal + the
  idempotency window backing the gateway's durability features.

The router deliberately imports no jax: it is a pure control/data-plane
process (the ``tpu-router.yaml`` Deployment runs it on a CPU node pool).
"""

from pyspark_tf_gke_tpu.router.client import parse_retry_after
from pyspark_tf_gke_tpu.router.discovery import (
    DOWN,
    DRAINING,
    UP,
    HealthProber,
    Replica,
    ReplicaSet,
    parse_replica_list,
    resolve_dns_replicas,
)
from pyspark_tf_gke_tpu.router.gateway import (
    RouterServer,
    start_router_http_server,
)
from pyspark_tf_gke_tpu.router.policy import affinity_key, choose_replica

__all__ = [
    "parse_retry_after",
    "UP", "DRAINING", "DOWN",
    "Replica", "ReplicaSet", "HealthProber",
    "parse_replica_list", "resolve_dns_replicas",
    "affinity_key", "choose_replica",
    "RouterServer", "start_router_http_server",
]
