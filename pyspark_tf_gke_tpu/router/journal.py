"""Front-owned stream-resume journal + idempotency cache.

Two bounded stores the gateway keeps so a single failure — a replica
dying mid-stream, a router↔client network blip, or an ambiguous 502 on
a blocking generate — no longer costs the client its request:

* :class:`StreamJournal` — one bounded ring of per-stream resume state
  (the PR 14 step-ring discipline: fixed capacity, front-owned, cheap
  appends under one lock). While the gateway relays a stream it
  journals every SSE event it wrote to the client (seq, raw payload,
  parsed token ids) plus everything a CONTINUATION needs if the
  replica dies mid-stream: the original request body, tenant, the
  deadline anchored at FIRST submit, and the accumulated emitted
  token IDS — the splice is token-id-level (``continuation:
  {emitted_ids}`` to the next replica), never re-tokenized text,
  which would be lossy for non-UTF-8 byte runs. A reconnecting client
  replays from ``Last-Event-ID`` + ``X-Request-Id`` against the same
  entry; live entries carry a condition so a follower attaches to a
  stream still being relayed.
* :class:`IdempotencyCache` — a bounded ``X-Idempotency-Key`` window
  for non-streamed ``/v1/generate``: the first request under a key
  executes, concurrent duplicates WAIT for its verdict, and a retry
  after the fact replays the cached 2xx response instead of
  generating twice. Non-2xx verdicts are never cached — a retry after
  a real failure must re-execute.

Both stores are in-router memory: bounded, self-evicting, and scoped
to the gateway process (a router restart forgets them — the client's
retry then degrades to today's behavior, never to corruption).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import List, Optional, Tuple

LIVE = "live"      # upstream leg(s) still delivering
DONE = "done"      # reached [DONE] (incl. relayed engine error terminals)
FAILED = "failed"  # upstream died and no resume could complete it


class StreamEntry:
    """One stream's resume state. The relay thread appends under the
    journal lock and notifies ``cond``; reconnect followers wait on it.
    ``events`` holds ``(seq, payload_json_str, n_tokens)`` for every
    ``data:`` event already written (or owed) to the client."""

    __slots__ = ("rid", "request", "tenant", "created", "deadline_at",
                 "events", "tokens", "token_ids", "last_text", "state",
                 "resumes", "cond", "evicted", "bytes", "seq")

    def __init__(self, rid: str, request: dict, tenant: str,
                 deadline_s: Optional[float] = None):
        self.rid = rid
        self.request = dict(request)
        self.tenant = tenant
        self.created = time.monotonic()
        # the ORIGINAL deadline, anchored at first submit: a resumed
        # continuation inherits what's left of it, never a fresh one
        self.deadline_at = (self.created + float(deadline_s)
                            if deadline_s is not None else None)
        self.events: List[Tuple[int, str, int]] = []
        self.tokens = 0
        self.token_ids: List[int] = []  # the continuation splice point
        self.last_text: Optional[str] = None  # running text (the
        #   synthesized-terminal completion when the budget was spent)
        self.state = LIVE
        self.resumes = 0
        self.cond = threading.Condition()
        self.evicted = False
        self.bytes = 0  # retained payload bytes (the ring's byte cap)
        self.seq = 0    # id-line counter; advances even after eviction
        #   (the client's ids must stay dense/monotonic either way)

    def remaining_deadline_s(self) -> Optional[float]:
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.monotonic()


class StreamJournal:
    """Bounded rid-keyed ring of :class:`StreamEntry` — bounded in
    ENTRIES (``max_entries``) and BYTES (``max_bytes``: the retained
    payloads dominate memory — each token event carries the cumulative
    ``text``, so one long stream's events are O(n²) bytes). Eviction
    prefers finished entries (oldest first); if every entry is still
    live the oldest live one is evicted anyway — the ring is a
    bounded-memory promise, not a durability one (an evicted live
    entry keeps relaying to its attached client; only reconnect
    replay is lost)."""

    def __init__(self, max_entries: int = 256,
                 max_bytes: int = 64 << 20,
                 obs: Optional[dict] = None):
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1 << 20, int(max_bytes))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, StreamEntry]" = OrderedDict()
        self._token_total = 0  # maintained incrementally: the gauges
        #   run on EVERY relayed token event, so an O(entries) rescan
        #   here would serialize all relay threads on the journal lock
        self._bytes_total = 0
        self._obs = obs or {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _gauges_locked(self) -> None:
        g = self._obs.get("router_stream_journal_entries")
        if g is not None:
            g.set(len(self._entries))
        g = self._obs.get("router_stream_journal_tokens")
        if g is not None:
            g.set(self._token_total)

    def _evict_locked(self, keep: Optional[StreamEntry] = None) -> None:
        """Evict (finished-first, else oldest) until both budgets
        hold. ``keep``: never evict the entry being appended to —
        one in-flight stream may exceed the byte budget alone (its
        own size is bounded by its max_new_tokens)."""
        def over():
            floor = 1 if keep is not None and not keep.evicted else 0
            return len(self._entries) > floor and (
                len(self._entries) > self.max_entries
                or self._bytes_total > self.max_bytes)

        while over():
            victim_key = next(
                (k for k, e in self._entries.items()
                 if e.state != LIVE and e is not keep), None)
            if victim_key is None:
                victim_key = next(k for k, e in self._entries.items()
                                  if e is not keep)
            victim = self._entries.pop(victim_key)
            victim.evicted = True  # its relay stops feeding the
            #   totals (the entry no longer counts toward the ring)
            self._token_total -= victim.tokens
            self._bytes_total -= victim.bytes

    def open(self, rid: str, request: dict, tenant: str,
             deadline_s: Optional[float] = None) -> StreamEntry:
        entry = StreamEntry(rid, request, tenant, deadline_s=deadline_s)
        with self._lock:
            self._entries[rid] = entry
            self._entries.move_to_end(rid)
            self._evict_locked(keep=entry)
            self._gauges_locked()
        return entry

    def append(self, entry: StreamEntry, payload: str,
               token_ids=(), text: Optional[str] = None) -> int:
        """Record one client-facing ``data:`` event; returns its seq
        (1-based, the ``id:`` line value). ``token_ids`` accumulate
        into the entry's splice point."""
        with entry.cond:
            entry.seq += 1
            seq = entry.seq
            if not entry.evicted:
                entry.events.append((seq, payload, len(token_ids)))
            # token_ids/last_text still accumulate after eviction —
            # the CONTINUATION splice needs them; only replay (the
            # payload retention) is what eviction gives up, so an
            # evicted live stream's payload bytes stop growing and
            # the max_bytes promise holds
            entry.token_ids.extend(int(t) for t in token_ids)
            if text is not None:
                entry.last_text = text
            entry.cond.notify_all()
        with self._lock:
            # per-entry size counters advance under the JOURNAL lock so
            # eviction (which subtracts them from the totals under the
            # same lock) can never race an increment into a drifting
            # total
            if not entry.evicted:
                entry.tokens += len(token_ids)
                entry.bytes += len(payload)
                self._token_total += len(token_ids)
                self._bytes_total += len(payload)
                if self._bytes_total > self.max_bytes:
                    self._evict_locked(keep=entry)
            self._gauges_locked()
        return seq

    def finish(self, entry: StreamEntry, state: str = DONE) -> None:
        with entry.cond:
            if entry.state == LIVE:
                entry.state = state
            entry.cond.notify_all()

    def get(self, rid: str) -> Optional[StreamEntry]:
        with self._lock:
            return self._entries.get(rid)

    def wait_events(self, entry: StreamEntry, after_seq: int,
                    timeout_s: float = 10.0
                    ) -> Tuple[List[Tuple[int, str, int]], str]:
        """Events with seq > ``after_seq`` plus the entry's state; when
        none are buffered and the entry is live, block up to
        ``timeout_s`` for the relay thread to append more. Seqs are
        dense 1-based, so the tail is a slice, not a scan."""
        cut = max(0, int(after_seq))
        with entry.cond:
            evs = entry.events[cut:]
            if not evs and entry.state == LIVE:
                entry.cond.wait(timeout_s)
                evs = entry.events[cut:]
            return list(evs), entry.state


class IdempotencyCache:
    """Bounded dedupe window for ``X-Idempotency-Key`` requests.

    :meth:`execute` runs ``fn`` at most once per key inside the
    window: the first caller executes, concurrent callers block on the
    executor's verdict, and later callers replay the cached result.
    Only 2xx results are cached (``fn`` returns ``(status, body,
    headers)``); any other verdict clears the key so a retry
    re-executes — the cache prevents DOUBLE generation, it never
    pins a failure."""

    def __init__(self, window_s: float = 300.0, max_entries: int = 1024):
        self.window_s = float(window_s)
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _IdemEntry]" = OrderedDict()

    def _evict_locked(self) -> None:
        now = time.monotonic()
        dead = [k for k, e in self._entries.items()
                if e.result is not None and e.expires_at <= now]
        for k in dead:
            del self._entries[k]
        while len(self._entries) > self.max_entries:
            victim = next(
                (k for k, e in self._entries.items()
                 if e.result is not None), None)
            if victim is None:
                break  # every entry in flight: over-cap but bounded by
                #        the router's own in-flight request count
            del self._entries[victim]

    def execute(self, key: str, fn, wait_timeout_s: float = 600.0):
        """Returns ``(result, replayed)``. ``replayed`` is True when
        the result came from the cache (or from waiting out a
        concurrent executor) instead of running ``fn``."""
        deadline = time.monotonic() + float(wait_timeout_s)
        while True:
            with self._lock:
                self._evict_locked()
                ent = self._entries.get(key)
                if ent is None:
                    ent = _IdemEntry()
                    self._entries[key] = ent
                    self._entries.move_to_end(key)
                    owner = True
                elif ent.result is not None:
                    return ent.result, True
                else:
                    owner = False
            if owner:
                try:
                    result = fn()
                except BaseException:
                    with self._lock:
                        if self._entries.get(key) is ent:
                            del self._entries[key]
                    ent.event.set()
                    raise
                with self._lock:
                    if 200 <= result[0] < 300:
                        ent.result = result
                        ent.expires_at = time.monotonic() + self.window_s
                    elif self._entries.get(key) is ent:
                        # non-2xx: drop the key — a retry re-executes
                        del self._entries[key]
                ent.event.set()
                return result, False
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not ent.event.wait(min(remaining, 5.0)):
                if time.monotonic() >= deadline:
                    # waited out the window: degrade to executing
                    # un-deduped rather than hanging the client forever
                    return fn(), False
            # woken (or polled): loop re-reads the entry — replay a
            # cached 2xx, or claim ownership if the executor failed


class _IdemEntry:
    __slots__ = ("result", "expires_at", "event")

    def __init__(self):
        self.result = None
        self.expires_at = 0.0
        self.event = threading.Event()
