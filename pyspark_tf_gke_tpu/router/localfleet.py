"""Local replica-fleet harness: the ONE copy of the launch scaffolding
shared by ``bench.py router``, ``tools/smoke_check.py --router``, and
the slow kill-one-replica soak in ``tests/test_router.py``.

All three drive the same contract — N tiny CPU ``BundleServer``
subprocesses behind the real router CLI — and before this module each
carried its own bundle-export recipe, port allocator, Popen argv, and
wait-for-healthy loop; a replica CLI flag change had to be edited three
times and would silently drift. Everything here is stdlib-only and
keeps the CALLING process jax-free: the tiny serving bundle is exported
by a CPU-pinned child process, so a bench/smoke parent never
initializes a jax backend (a down TPU tunnel must not gate a
router-plane check).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from typing import Optional, Sequence

from pyspark_tf_gke_tpu.replay.stats import pct

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# byte-tokenizer-compatible CausalLM (vocab 259 covers the byte range);
# small enough that two replicas + a router fit a 1-vCPU box
TINY_BUNDLE_EXPORT_SRC = (
    "import jax, sys\n"
    "jax.config.update('jax_platforms', 'cpu')\n"
    "import jax.numpy as jnp\n"
    "from flax import linen as nn\n"
    "from pyspark_tf_gke_tpu.models import CausalLM, CausalLMConfig\n"
    "from pyspark_tf_gke_tpu.train.export import export_serving_bundle\n"
    "from pyspark_tf_gke_tpu.utils.seeding import make_rng\n"
    "cfg = CausalLMConfig(vocab_size=259, hidden_size=32,\n"
    "                     num_layers=2, num_heads=2,\n"
    "                     intermediate_size=64, max_seq_len=64,\n"
    "                     dtype=jnp.float32)\n"
    "model = CausalLM(cfg)\n"
    "params = nn.meta.unbox(jax.jit(model.init)(\n"
    "    make_rng(0), jnp.zeros((1, 8), jnp.int32))['params'])\n"
    "export_serving_bundle(cfg, params, sys.argv[1], quantize=False)\n")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def cpu_env() -> dict:
    return dict(os.environ, JAX_PLATFORMS="cpu")


# PAGED variant of the tiny bundle: same weights recipe, but exported
# with KV page-pool geometry so serve's --prefix-cache routes to the
# engine-level radix cache — the precondition for the disaggregated
# KV-page handoff (export/import rides the radix trie). The model is
# BUILT dense (init needs no pool) and EXPORTED paged, the same shape
# smoke_check's --prefix-cache check uses.
TINY_PAGED_BUNDLE_EXPORT_SRC = (
    "import dataclasses, jax, sys\n"
    "jax.config.update('jax_platforms', 'cpu')\n"
    "import jax.numpy as jnp\n"
    "from flax import linen as nn\n"
    "from pyspark_tf_gke_tpu.models import CausalLM, CausalLMConfig\n"
    "from pyspark_tf_gke_tpu.train.export import export_serving_bundle\n"
    "from pyspark_tf_gke_tpu.utils.seeding import make_rng\n"
    "cfg = CausalLMConfig(vocab_size=259, hidden_size=32,\n"
    "                     num_layers=2, num_heads=2,\n"
    "                     intermediate_size=64, max_seq_len=256,\n"
    "                     kv_page_size=32, kv_num_pages=32,\n"
    "                     dtype=jnp.float32)\n"
    "model = CausalLM(dataclasses.replace(cfg, kv_num_pages=None))\n"
    "params = nn.meta.unbox(jax.jit(model.init)(\n"
    "    make_rng(0), jnp.zeros((1, 8), jnp.int32))['params'])\n"
    "export_serving_bundle(cfg, params, sys.argv[1], quantize=False)\n")


def export_tiny_bundle(dest: str, timeout_s: float = 600.0,
                       paged: bool = False) -> str:
    """Export the tiny serving bundle via a CPU-pinned child process
    (the caller's jax stays un-initialized). ``paged=True`` exports
    the paged-KV variant (radix cache, KV-page handoff)."""
    proc = subprocess.run(
        [sys.executable, "-c",
         TINY_PAGED_BUNDLE_EXPORT_SRC if paged
         else TINY_BUNDLE_EXPORT_SRC, dest],
        env=cpu_env(), cwd=REPO_ROOT, capture_output=True, text=True,
        timeout=timeout_s)
    if proc.returncode != 0:
        raise RuntimeError(f"bundle export failed: {proc.stderr[-800:]}")
    return dest


def launch_replica(bundle: str, port: int,
                   extra_args: Sequence[str] = (),
                   quiet: bool = True) -> subprocess.Popen:
    """One CPU-pinned ``train.serve`` replica on 127.0.0.1:port."""
    kw = ({"stdout": subprocess.DEVNULL, "stderr": subprocess.DEVNULL}
          if quiet else {})
    return subprocess.Popen(
        [sys.executable, "-m", "pyspark_tf_gke_tpu.train.serve",
         "--bundle", bundle, "--host", "127.0.0.1", "--port", str(port),
         "--continuous-slots", "2", "--continuous-chunk", "2",
         *extra_args],
        env=cpu_env(), cwd=REPO_ROOT, **kw)


def launch_router(replica_ports: Sequence[int], port: int,
                  extra_args: Sequence[str] = (),
                  quiet: bool = True) -> subprocess.Popen:
    """The real router CLI fronting ``replica_ports``, tuned for local
    checks: tight probe interval, single-failure DOWN."""
    kw = ({"stdout": subprocess.DEVNULL, "stderr": subprocess.DEVNULL}
          if quiet else {})
    return subprocess.Popen(
        [sys.executable, "-m", "pyspark_tf_gke_tpu.router",
         "--host", "127.0.0.1", "--port", str(port),
         "--replicas", ",".join(f"http://127.0.0.1:{p}"
                                for p in replica_ports),
         "--probe-interval", "0.2", "--fail-threshold", "1",
         *extra_args],
        env=dict(os.environ), cwd=REPO_ROOT, **kw)


def wait_healthy(base_url: str, deadline: float,
                 proc: Optional[subprocess.Popen] = None) -> None:
    """Poll ``/healthz`` until 200 or ``deadline`` (epoch seconds);
    fail fast if ``proc`` exits before answering."""
    while True:
        try:
            urllib.request.urlopen(base_url + "/healthz", timeout=2)
            return
        except Exception:  # noqa: BLE001 — still booting
            if proc is not None and proc.poll() is not None:
                raise RuntimeError(
                    f"{base_url} process died at startup "
                    f"(rc={proc.returncode})")
            if time.time() > deadline:
                raise RuntimeError(f"{base_url} never became healthy")
            time.sleep(0.3)


def post_generate(base_url: str, prompt: str, max_new_tokens: int = 6,
                  timeout_s: float = 120.0) -> dict:
    req = urllib.request.Request(
        base_url + "/v1/generate",
        data=json.dumps({"prompts": [prompt],
                         "max_new_tokens": max_new_tokens}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def post_tenant(base_url: str, prompt: str, tenant: str,
                max_new_tokens: int = 6, timeout_s: float = 120.0):
    """One tenant-tagged generate that NEVER raises on an HTTP error
    verdict: returns ``(status, body, latency_ms)`` — 429s are data to
    the fairness scenarios, not exceptions. Transport failures return
    status 0 with the error string in the body."""
    import urllib.error

    req = urllib.request.Request(
        base_url + "/v1/generate",
        data=json.dumps({"prompts": [prompt],
                         "max_new_tokens": max_new_tokens}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Tenant": tenant})
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            body = json.loads(resp.read())
            status = resp.status
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read() or b"{}")
        except ValueError:
            body = {}
        body.setdefault("retry_after", exc.headers.get("Retry-After"))
        body.setdefault("tenant_shed", exc.headers.get("X-Tenant-Shed"))
        status = exc.code
    except Exception as exc:  # noqa: BLE001 — transport failure is an
        #   outcome the scenarios assert on, not a crash
        return 0, {"error": repr(exc)}, (time.monotonic() - t0) * 1000.0
    return status, body, (time.monotonic() - t0) * 1000.0


def run_noisy_neighbor(url: str, *, light_requests: int = 10,
                       light_budget: int = 6, flood_threads: int = 3,
                       flood_budget: int = 12,
                       light_prompt: str = "light request",
                       mid_flood_hook=None,
                       timeout_s: float = 120.0) -> dict:
    """THE noisy-neighbor scenario, shared by ``tools/smoke_check.py
    --fairness`` and the slow chaos soak in ``tests/test_fairness.py``:
    ``flood_threads`` greedy "noisy"-tenant loops hammer ``url`` while
    the "light" tenant runs ``light_requests`` serial generates.
    ``mid_flood_hook`` (optional) fires once, halfway through the light
    sequence — the scale-up/down injection point (start or SIGKILL a
    replica). Returns per-tenant outcome tallies + the light tenant's
    latency list; every request reaches a terminal outcome before this
    returns (the flood stops and joins)."""
    import threading

    out = {
        "light": {"ok": 0, "lat_ms": [], "errors": []},
        "noisy": {"ok": 0, "tenant_429": 0, "other_429": 0,
                  "shed_503": 0, "errors": []},
        "noisy_attempts": 0,
    }
    lock = threading.Lock()
    stop = threading.Event()

    def flood(i: int):
        n = 0
        while not stop.is_set():
            status, body, _dt = post_tenant(
                url, f"noisy {i} {n}", "noisy",
                max_new_tokens=flood_budget, timeout_s=timeout_s)
            n += 1
            with lock:
                out["noisy_attempts"] += 1
                if status == 200:
                    out["noisy"]["ok"] += 1
                elif status == 429 and (
                        str(body.get("reason", "")).startswith("tenant_")
                        or body.get("tenant_shed")):
                    out["noisy"]["tenant_429"] += 1
                elif status == 429:
                    out["noisy"]["other_429"] += 1
                elif status == 503:
                    # router/replica drain or no-replica blips during a
                    # scale event: terminal, counted, not a loss
                    out["noisy"]["shed_503"] += 1
                else:
                    out["noisy"]["errors"].append((status, str(body)[:200]))
            if status == 429:
                time.sleep(0.05)  # a real client honors Retry-After;
                #   a zero-sleep loop would just measure socket churn

    threads = [threading.Thread(target=flood, args=(i,), daemon=True)
               for i in range(flood_threads)]
    for t in threads:
        t.start()
    try:
        for i in range(light_requests):
            if mid_flood_hook is not None and i == light_requests // 2:
                mid_flood_hook()
            status, body, dt = post_tenant(
                url, f"{light_prompt} {i}", "light",
                max_new_tokens=light_budget, timeout_s=timeout_s)
            if status == 200:
                out["light"]["ok"] += 1
                out["light"]["lat_ms"].append(dt)
            else:
                out["light"]["errors"].append((status, str(body)[:200]))
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=timeout_s)
    return out


def percentile(xs, q: float) -> float:
    """Nearest-rank percentile of a latency list (0 when empty).
    Thin wrapper over ``replay/stats.pct`` — the ONE percentile
    implementation site — keeping this module's historical empty-list
    contract (0.0, not None)."""
    v = pct(list(xs), q)
    return 0.0 if v is None else v


class LocalFleet:
    """Context manager owning one complete local fleet: a tiny bundle
    export, N CPU replica subprocesses and (optionally) the real
    router CLI in front — the setup every fleet-level check repeats
    (``bench.py replay``, ``smoke_check --replay``, ``tools/replay.py
    run --localfleet``). Exit kills every process and removes the
    temp dir; a partially-failed boot cleans up the same way."""

    def __init__(self, n_replicas: int = 2, *, router: bool = True,
                 replica_args: Sequence[str] = (),
                 per_replica_args: Optional[
                     Sequence[Sequence[str]]] = None,
                 router_args: Sequence[str] = (),
                 bundle: Optional[str] = None, paged: bool = False,
                 boot_timeout_s: float = 600.0, quiet: bool = True):
        self.n_replicas = int(n_replicas)
        self.with_router = router
        self.replica_args = tuple(replica_args)
        # per-index extra args APPENDED to replica_args — the role-split
        # fleet shape (replica 0 `--role prefill`, the rest `--role
        # decode`); a restart keeps its index's args, a scale-up beyond
        # the list gets the shared args only
        self.per_replica_args = (None if per_replica_args is None else
                                 tuple(tuple(a) for a in per_replica_args))
        if (self.per_replica_args is not None
                and len(self.per_replica_args) != self.n_replicas):
            raise ValueError("per_replica_args must have one entry "
                             "per replica")
        self.router_args = tuple(router_args)
        self.bundle = bundle  # pre-exported dir to reuse (callers
        #   booting several fleets pay the export once)
        self.paged = bool(paged)  # export the paged-KV tiny bundle
        #   (radix cache + KV-page handoff) when self-exporting
        self.boot_timeout_s = float(boot_timeout_s)
        self.quiet = quiet
        self.procs: list = []
        self.router_proc: Optional[subprocess.Popen] = None
        self.replica_ports: list = []
        self.router_port: Optional[int] = None
        self._tmp: Optional[str] = None

    def _args_for(self, i: int) -> tuple:
        extra = (self.per_replica_args[i]
                 if self.per_replica_args is not None
                 and i < len(self.per_replica_args) else ())
        return self.replica_args + tuple(extra)
        self._bundle_dir: Optional[str] = None  # retained for restarts

    @property
    def url(self) -> str:
        """The fleet's front door (router when present, else the
        first replica)."""
        port = (self.router_port if self.with_router
                else self.replica_ports[0])
        return f"http://127.0.0.1:{port}"

    @property
    def replica_urls(self) -> list:
        return [f"http://127.0.0.1:{p}" for p in self.replica_ports]

    def warm(self, prompts: Sequence[str] = ("warm a", "warm b"),
             max_new_tokens: int = 4) -> None:
        """Hit each replica DIRECTLY (routed warms can all land on one
        replica via affinity), so first-request JIT compiles never
        land inside a caller's timed run."""
        for rurl in self.replica_urls:
            for prompt in prompts:
                post_generate(rurl, prompt,
                              max_new_tokens=max_new_tokens)

    def wait_idle(self, timeout_s: float = 60.0) -> bool:
        """Poll every replica's ``/loadz`` until the whole fleet
        reports an empty engine (``queued == 0 and active == 0``) or
        the timeout passes; returns whether it quiesced. A replica
        still grinding a previous scenario's backlog steals the
        shared core from whatever the caller measures next, so
        fleet-level checks quiesce between phases. Transient poll
        errors count as busy (a saturated replica answering late is
        exactly the not-idle case)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            idle = True
            for rurl in self.replica_urls:
                try:
                    with urllib.request.urlopen(rurl + "/loadz",
                                                timeout=5) as resp:
                        lz = json.loads(resp.read())
                    if lz["queued"] or lz["active"]:
                        idle = False
                except Exception:  # noqa: BLE001 — late answer = busy
                    idle = False
            if idle:
                return True
            time.sleep(0.3)
        return False

    # -- chaos hooks (chaos/runner.py drives these at scheduled offsets) --

    def kill_replica(self, i: int) -> None:
        """SIGKILL replica ``i`` (the pod-death shape: no drain, no
        goodbye — in-flight requests to it fail at the transport)."""
        proc = self.procs[i]
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)

    def stop_replica(self, i: int) -> None:
        """SIGSTOP replica ``i``: alive but unresponsive — the local
        stand-in for a hung host AND a network partition (probes time
        out, open streams stall). Pair with :meth:`cont_replica`."""
        import signal

        self.procs[i].send_signal(signal.SIGSTOP)

    def cont_replica(self, i: int) -> None:
        import signal

        if self.procs[i].poll() is None:
            self.procs[i].send_signal(signal.SIGCONT)

    # -- scale hooks (router/autopilot.py actuates through these) --------

    def start_replica(self) -> str:
        """Boot ONE additional replica (the scale-up actuation shape):
        fresh port, same bundle and args, appended to
        ``procs``/``replica_ports``; returns its base URL once
        ``/healthz`` answers. The caller registers it with the router
        (POST /admin/replicas) — a booted-but-unregistered replica
        receives no traffic."""
        if self._bundle_dir is None:
            raise RuntimeError("fleet never booted")
        port = free_port()
        proc = launch_replica(self._bundle_dir, port,
                              extra_args=self._args_for(len(self.procs)),
                              quiet=self.quiet)
        self.replica_ports.append(port)
        self.procs.append(proc)
        self.n_replicas = len(self.procs)
        url = f"http://127.0.0.1:{port}"
        wait_healthy(url, time.time() + self.boot_timeout_s, proc)
        return url

    def drain_replica(self, i: int, timeout_s: float = 30.0) -> bool:
        """SIGTERM replica ``i`` — the graceful-eviction shape: serve's
        drain path finishes in-flight work, then the process exits.
        Returns whether it exited within ``timeout_s`` (False = still
        draining, e.g. the hung-drain chaos case — the caller decides
        whether to escalate to :meth:`kill_replica`)."""
        import signal

        proc = self.procs[i]
        if proc.poll() is not None:
            return True
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=timeout_s)
            return True
        except subprocess.TimeoutExpired:
            return False

    def restart_replica(self, i: int) -> None:
        """Relaunch replica ``i`` on its ORIGINAL port and args (the
        k8s pod-replacement shape: same Service endpoint, fresh
        process) and wait until it answers /healthz."""
        if self._bundle_dir is None:
            raise RuntimeError("fleet never booted")
        if self.procs[i].poll() is None:
            self.kill_replica(i)
        self.procs[i] = launch_replica(
            self._bundle_dir, self.replica_ports[i],
            extra_args=self._args_for(i), quiet=self.quiet)
        wait_healthy(self.replica_urls[i],
                     time.time() + self.boot_timeout_s, self.procs[i])

    def __enter__(self) -> "LocalFleet":
        import tempfile

        self._tmp = tempfile.mkdtemp(prefix="localfleet-")
        try:
            bundle = self.bundle or export_tiny_bundle(
                os.path.join(self._tmp, "bundle"),
                timeout_s=self.boot_timeout_s, paged=self.paged)
            self._bundle_dir = bundle
            self.replica_ports = [free_port()
                                  for _ in range(self.n_replicas)]
            self.procs = [launch_replica(bundle, p,
                                         extra_args=self._args_for(i),
                                         quiet=self.quiet)
                          for i, p in enumerate(self.replica_ports)]
            deadline = time.time() + self.boot_timeout_s
            if self.with_router:
                self.router_port = free_port()
                self.router_proc = launch_router(
                    self.replica_ports, self.router_port,
                    extra_args=self.router_args, quiet=self.quiet)
            for p, proc in zip(self.replica_ports, self.procs):
                wait_healthy(f"http://127.0.0.1:{p}", deadline, proc)
            if self.router_proc is not None:
                wait_healthy(self.url, deadline, self.router_proc)
        except BaseException:
            self.__exit__(None, None, None)
            raise
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        import shutil

        for p in [self.router_proc, *self.procs]:
            if p is not None and p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        if self._tmp:
            shutil.rmtree(self._tmp, ignore_errors=True)
