"""pyspark_tf_gke_tpu — a TPU-native ML-platform framework.

A from-scratch re-design of the capabilities of the reference repo
``greg-ogs/PySpark-TF-GKE`` for TPU hardware:

* **Training plane** (replacing ``workloads/raw-tf``): JAX/XLA (PjRT TPU
  runtime) with ``jax.jit``/``shard_map`` over a ``jax.sharding.Mesh``.
  Parallelism is a compile-time sharding decision — every worker runs the
  same SPMD program; gradients are combined with XLA collectives over ICI
  instead of the reference's asynchronous parameter-server push/pull over
  gRPC (reference: ``workloads/raw-tf/train_tf_ps.py:440-511``).
* **Data plane**: host-side loaders with the exact semantics of the
  reference's CSV/image loaders (``train_tf_ps.py:75-149, 200-322``),
  per-host sharding (the ``InputContext.shard`` analog), and a TFRecord
  bridge so a PySpark ETL pool can feed TPU workers.
* **ETL plane** (replacing ``workloads/raw-spark``): the PySpark workloads
  are preserved behind import gates, and a TPU-native KMeans + feature
  pipeline (``etl/``) runs the same classical-ML workload on the MXU.
* **Infra plane** (replacing ``infra/``): Terraform for a TPU v5e GKE node
  pool and k8s manifests in ``infra/`` at the repo root.

Subpackages
-----------
``utils``     config/flags, logging, seeding, small helpers
``parallel``  mesh construction, sharding rules, distributed bootstrap
``models``    MLP / CNN (parity oracles), ResNet-50, BERT-base
``ops``       attention (blockwise + ring), Pallas TPU kernels
``data``      CSV / image / synthetic loaders, host pipeline, TFRecord bridge
``train``     train step, loop, metrics, checkpointing, CLI
``obs``       unified metrics registry + event trail (docs/OBSERVABILITY.md)
``etl``       TPU-native KMeans + gated PySpark workloads
``evaluate``  saved-model visual checker
"""

__version__ = "0.1.0"

from pyspark_tf_gke_tpu.utils.config import Config  # noqa: F401
