"""Default in-process stage set for the pipeline coordinator.

One callable per stage, all configured by :class:`LocalPipelineConfig`.
The coordinator itself is jax-free; these local stages lazy-import the
data/train planes INSIDE their bodies, so building the stage map costs
nothing and a deployment that swaps a stage for a k8s-Job launcher
never pays for the planes it doesn't run in-process.

The local set closes the loop end to end on one box (the smoke gate
``tools/smoke_check.py --pipeline`` and the CPU tests drive it):

* **ingest** — materialize ``rows_per_round`` packed-token rows as
  native TFRecord shards (parallel writer) and append them to the
  shard manifest as one new generation. The row source is pluggable
  (``row_source``); the default synthesizes byte-tokenizer text so the
  loop runs anywhere.
* **train** — build-or-restore the tiny CausalLM + Trainer, tail the
  manifest through :class:`~pyspark_tf_gke_tpu.data.native_tfrecord.
  ManifestTailSource` (new generations join at epoch boundaries;
  ``consumed_batches`` persists in the coordinator state so a restart
  resumes the EXACT deterministic batch stream mid-epoch), run
  ``steps_per_round`` optimizer steps, checkpoint.
* **export** — write the serving bundle for this round's generation
  (``bundles/gen-NNNN``), quantization off by default at toy scale.
* **publish** — rolling hot-swap across the serving fleet via
  :func:`pyspark_tf_gke_tpu.pipeline.publish.rolling_publish`; with no
  replicas configured the stage is a no-op (bundle still lands on disk
  for a later fleet).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, Optional, Sequence

from pyspark_tf_gke_tpu.pipeline.manifest import ShardSetManifest
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("pipeline.stages")


@dataclasses.dataclass
class LocalPipelineConfig:
    """Knobs for the in-process stage set (CLI maps env/flags here)."""

    work_dir: str
    # ingest
    rows_per_round: int = 2048
    seq_len: int = 64
    num_shards: int = 4
    tokenizer: str = "byte"
    row_source: Optional[Callable[[int, "LocalPipelineConfig"], dict]] = None
    # train
    steps_per_round: int = 8
    batch_size: int = 8
    learning_rate: float = 1e-3
    hidden_size: int = 32
    num_layers: int = 2
    num_heads: int = 2
    intermediate_size: int = 64
    # export
    quantize: bool = False
    # publish
    replicas: Sequence[str] = ()
    admin_token: str = ""
    max_unavailable: int = 1
    confirm_timeout_s: float = 60.0
    canary: bool = True
    # how REPLICAS address a published bundle, when that differs from
    # the coordinator's local path — e.g. work_dir is a GCS FUSE mount
    # and the fleet pulls gs:// URLs (the serve side's _resolve_bundle
    # spools remote bundles locally): "gs://bucket/pipeline/loop/bundles"
    bundle_url_prefix: str = ""

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.work_dir, "shards", "manifest.jsonl")

    @property
    def checkpoint_dir(self) -> str:
        return os.path.join(self.work_dir, "checkpoints")

    def bundle_dir(self, generation: int) -> str:
        return os.path.join(self.work_dir, "bundles", f"gen-{generation:04d}")


def _synthetic_rows(round_no: int, cfg: LocalPipelineConfig) -> dict:
    """Default row source: deterministic-per-round pseudo-text packed to
    ``seq_len`` token rows — enough signal for the loss to move and for
    every round's data (and therefore weights) to differ."""
    import numpy as np

    from pyspark_tf_gke_tpu.data.text import get_tokenizer, pack_tokens

    tokenizer = get_tokenizer(cfg.tokenizer)
    rng = np.random.default_rng(1000 + round_no)
    words = ["spark", "tpu", "shard", "bundle", "train", "serve",
             f"round{round_no}", "pipeline", "manifest", "publish"]
    docs = (" ".join(rng.choice(words, size=12)) for _ in
            range(max(1, cfg.rows_per_round // 4)))
    rows = []
    for packed in pack_tokens(docs, tokenizer, cfg.seq_len):
        rows.append(np.asarray(packed, dtype=np.int64))
        if len(rows) >= cfg.rows_per_round:
            break
    return {"input_ids": np.stack(rows)}


def ingest_stage(cfg: LocalPipelineConfig):
    def ingest(state, outputs) -> dict:
        from pyspark_tf_gke_tpu.data.native_tfrecord import (
            write_tfrecord_shards,
        )

        manifest = ShardSetManifest(cfg.manifest_path)
        # idempotent at round granularity: a crash AFTER the append but
        # BEFORE the coordinator persisted the stage would otherwise
        # re-append the same rows as a duplicate generation on resume,
        # skewing every later epoch's length and the consumed-batches
        # resume accounting
        for rec in manifest.records():
            if rec.get("round") == state.round:
                logger.info(
                    "ingest round %d: generation %d already landed; "
                    "resuming without re-appending", state.round,
                    rec["generation"])
                return {"data_generation": int(rec["generation"]),
                        "rows": rec.get("rows"),
                        "landed_at": rec["landed_at"]}
        source = cfg.row_source or _synthetic_rows
        arrays = source(state.round, cfg)
        n = len(next(iter(arrays.values())))
        prefix = os.path.join(cfg.work_dir, "shards",
                              f"round-{state.round:04d}")
        paths = write_tfrecord_shards(arrays, prefix,
                                      num_shards=cfg.num_shards)
        from pyspark_tf_gke_tpu.obs.trace import current_trace_id

        # round-level lineage: the coordinator's round trace id rides
        # the manifest meta, so a shard generation joins the trace
        # that produced it (and, via export, the serving bundle)
        meta = {"rows": n, "round": state.round}
        if current_trace_id():
            meta["trace_id"] = current_trace_id()
        gen = manifest.append(paths, meta=meta)
        logger.info("ingest round %d: %d rows -> %d shards "
                    "(data generation %d)", state.round, n, len(paths), gen)
        return {"data_generation": gen, "rows": n,
                "landed_at": time.time()}

    return ingest


def _build_trainer(cfg: LocalPipelineConfig):
    """The one model/trainer construction recipe the train and export
    stages share, plus a zero-sample initial state — a config knob
    threaded through only one of them would silently rebuild a model
    whose shapes mismatch the trained checkpoint."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pyspark_tf_gke_tpu.data.text import get_tokenizer
    from pyspark_tf_gke_tpu.models import CausalLM, CausalLMConfig
    from pyspark_tf_gke_tpu.parallel.mesh import make_mesh
    from pyspark_tf_gke_tpu.train.trainer import TASKS, Trainer
    from pyspark_tf_gke_tpu.utils.seeding import make_rng

    tokenizer = get_tokenizer(cfg.tokenizer)
    model_cfg = CausalLMConfig(
        vocab_size=tokenizer.vocab_size, hidden_size=cfg.hidden_size,
        num_layers=cfg.num_layers, num_heads=cfg.num_heads,
        intermediate_size=cfg.intermediate_size,
        max_seq_len=cfg.seq_len, dtype=jnp.float32)
    model = CausalLM(model_cfg)
    mesh = make_mesh({"dp": 1}, jax.devices()[:1])
    trainer = Trainer(model, TASKS["causal_lm"](), mesh,
                      learning_rate=cfg.learning_rate)
    sample = {"input_ids": np.zeros((cfg.batch_size, cfg.seq_len),
                                    np.int32)}
    state0 = trainer.init_state(make_rng(0), sample)
    return model_cfg, trainer, state0


def train_stage(cfg: LocalPipelineConfig):
    def train(state, outputs) -> dict:
        import jax
        import numpy as np

        from pyspark_tf_gke_tpu.data.native_tfrecord import (
            ManifestTailSource,
        )
        from pyspark_tf_gke_tpu.data.tfrecord import schema_for
        from pyspark_tf_gke_tpu.train.checkpoint import CheckpointManager

        _, trainer, state0 = _build_trainer(cfg)

        # the tail source resumes the deterministic batch stream at the
        # coordinator-persisted offset — a restarted coordinator
        # continues mid-stream instead of re-training from row 0
        consumed = int((state.extra.get("train_progress") or {}).get(
            "consumed_batches", 0))
        schema = schema_for(
            {"input_ids": np.zeros((1, cfg.seq_len), np.int64)})
        source = ManifestTailSource(
            cfg.manifest_path, schema, cfg.batch_size,
            consumed_batches=consumed, wait_timeout_s=60.0)

        ckpt = CheckpointManager(cfg.checkpoint_dir)
        try:
            if ckpt.latest_step() is not None:
                state0 = ckpt.restore(state0)
            # prefetch=0: the device-prefetch worker would draw AHEAD of
            # the optimizer, inflating consumed_batches past the steps
            # actually trained — exact stream resume needs the two equal
            st, history = trainer.fit(
                state0, source, epochs=1,
                steps_per_epoch=cfg.steps_per_round, prefetch=0)
            ckpt.save(st, history, force=True)
            ckpt.wait()
        finally:
            ckpt.close()
        loss = float(history["loss"][-1]) if history.get("loss") else None
        # survives the round-end outputs reset: next round's train
        # stage resumes the deterministic stream here
        state.extra["train_progress"] = {
            "consumed_batches": source.consumed_batches}
        return {"consumed_batches": source.consumed_batches,
                "global_step": int(jax.device_get(st.step)),
                "loss": loss}

    return train


def export_stage(cfg: LocalPipelineConfig):
    def export(state, outputs) -> dict:
        from pyspark_tf_gke_tpu.train.checkpoint import CheckpointManager
        from pyspark_tf_gke_tpu.train.export import export_serving_bundle

        model_cfg, _, st = _build_trainer(cfg)
        ckpt = CheckpointManager(cfg.checkpoint_dir)
        try:
            if ckpt.latest_step() is None:
                raise FileNotFoundError(
                    f"no checkpoint under {cfg.checkpoint_dir} — did the "
                    "train stage run?")
            st = ckpt.restore(st)
        finally:
            ckpt.close()
        generation = state.round  # one bundle generation per round
        out_dir = cfg.bundle_dir(generation)
        from pyspark_tf_gke_tpu.obs.trace import current_trace_id

        extra_meta = {"pipeline_generation": generation,
                      "pipeline_round": state.round}
        if current_trace_id():
            # a replica serving this bundle advertises a generation
            # whose producing round is one /traces (or trail) lookup
            # away — the serving plane's lineage back-pointer
            extra_meta["trace_id"] = current_trace_id()
        export_serving_bundle(model_cfg, st.params, out_dir,
                              quantize=cfg.quantize,
                              tokenizer_spec=cfg.tokenizer,
                              extra_meta=extra_meta)
        logger.info("export round %d: bundle generation %d -> %s",
                    state.round, generation, out_dir)
        return {"bundle_dir": out_dir, "generation": generation}

    return export


def publish_stage(cfg: LocalPipelineConfig):
    def publish(state, outputs) -> dict:
        from pyspark_tf_gke_tpu.pipeline.coordinator import (
            resolve_replicas,
        )

        export_out = outputs.get("export") or {}
        bundle_dir = export_out.get("bundle_dir")
        generation = int(export_out.get("generation", state.round))
        # dns:// entries re-resolve EVERY round: a long-running
        # coordinator must publish to the fleet as it is now (HPA
        # scale-ups, rescheduled pods), not a boot-time snapshot
        replicas = resolve_replicas(",".join(cfg.replicas))
        if not replicas:
            logger.info("publish round %d: no replicas configured; "
                        "bundle generation %d stays on disk",
                        state.round, generation)
            return {"published": 0, "generation": generation,
                    "results": []}
        if not bundle_dir:
            raise ValueError("publish has no bundle_dir from export")
        if cfg.bundle_url_prefix:
            bundle_dir = (cfg.bundle_url_prefix.rstrip("/") + "/"
                          + os.path.basename(bundle_dir.rstrip("/")))
        from pyspark_tf_gke_tpu.pipeline.publish import rolling_publish

        report = rolling_publish(
            replicas, bundle_dir, generation,
            token=cfg.admin_token,
            max_unavailable=cfg.max_unavailable,
            confirm_timeout_s=cfg.confirm_timeout_s,
            canary=cfg.canary)
        if not report["ok"]:
            raise RuntimeError(
                f"rolling publish of generation {generation} failed: "
                f"{report['results']}")
        return {"published": report["published"],
                "generation": generation, "results": report["results"]}

    return publish


def make_local_stages(cfg: LocalPipelineConfig) -> Dict[str, Callable]:
    os.makedirs(cfg.work_dir, exist_ok=True)
    return {
        "ingest": ingest_stage(cfg),
        "train": train_stage(cfg),
        "export": export_stage(cfg),
        "publish": publish_stage(cfg),
    }
