"""Shard-set manifest: the incremental ETL→train hand-off.

The batch-shaped planes exchange data by glob convention (``prefix-*``
patterns); a CONTINUOUS loop needs an explicit, ordered record of which
shards are COMPLETE — a half-written TFRecord file matching the glob
would feed the trainer torn protos. :class:`ShardSetManifest` is that
record: a JSONL file where each line is one *generation* — a set of
finished shard paths plus metadata, stamped with a monotonically
increasing generation number and a wall-clock landing time.

Durability/atomicity contract (what the tests pin):

* appends rewrite the whole file to a temp sibling, ``fsync`` it, and
  ``os.replace`` onto the manifest path — a reader (the trainer's
  ``tail_shards`` source, possibly in another process) always sees a
  complete, parseable file: either the pre-append or the post-append
  state, never a torn line;
* generation numbers are assigned under an ``fcntl`` file lock (plus a
  process-local mutex), so concurrent appenders — N Spark bridge
  executors landing shards — get distinct, strictly increasing
  generations;
* reads take no lock at all: the rename is the synchronization.

Producers call :meth:`append` AFTER their shard files are fully
written and closed (the ``etl/`` bridges and
``data.native_tfrecord.write_tfrecord_shards`` both finish their
writes before returning paths). Consumers poll :meth:`generation` /
:meth:`shards` — cheap (one small file read) and safe at any moment.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

MANIFEST_FORMAT = "pyspark_tf_gke_tpu.shard_manifest.v1"


def write_atomic_json(path: str, payload: dict) -> None:
    """tmp + fsync + rename: the one durable-small-state write used by
    the manifest and the coordinator's resume state file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


class ShardSetManifest:
    """Append-only JSONL manifest of completed TFRecord shard sets."""

    def __init__(self, path: str):
        self.path = str(path)
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        self._mutex = threading.Lock()  # in-process appenders
        self._lock_path = f"{self.path}.lock"

    # -- reading (lock-free) --------------------------------------------

    def records(self) -> List[dict]:
        """Every generation record, in append order. A torn TRAILING
        line (possible only if a writer bypassed the atomic-rename
        contract) is dropped rather than failing the tail."""
        try:
            with open(self.path) as fh:
                raw = fh.read()
        except FileNotFoundError:
            return []
        out: List[dict] = []
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                break  # incomplete tail — everything before it is valid
        return out

    def generation(self) -> int:
        """Latest generation number (0 = empty manifest)."""
        recs = self.records()
        return int(recs[-1]["generation"]) if recs else 0

    def shards(self, since_generation: int = 0) -> List[str]:
        """All shard paths in generations > ``since_generation``, in
        generation order (within a generation, producer order)."""
        out: List[str] = []
        for rec in self.records():
            if int(rec["generation"]) > int(since_generation):
                out.extend(rec["shards"])
        return out

    def wait_for_generation(self, generation: int, timeout_s: float,
                            poll_s: float = 0.05) -> bool:
        """Block until the manifest reaches ``generation`` (True) or
        ``timeout_s`` elapses (False) — the trainer's cold-start gate."""
        deadline = time.monotonic() + float(timeout_s)
        while self.generation() < int(generation):
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)
        return True

    # -- appending ------------------------------------------------------

    def append(self, shards: Sequence[str],
               meta: Optional[Dict] = None) -> int:
        """Record one completed shard set; returns its generation.

        Safe against concurrent appenders in this process (mutex) and
        across processes (``fcntl.flock`` on a sidecar lock file): the
        generation is read, incremented, and the rewritten file renamed
        in, all inside the critical section."""
        shards = [str(s) for s in shards]
        if not shards:
            raise ValueError("refusing to append an empty shard set")
        with self._mutex:
            lock_fh = open(self._lock_path, "a+")
            try:
                try:
                    import fcntl

                    fcntl.flock(lock_fh.fileno(), fcntl.LOCK_EX)
                except ImportError:  # non-POSIX: mutex-only
                    pass
                recs = self.records()
                gen = (int(recs[-1]["generation"]) if recs else 0) + 1
                rec = {
                    **(meta or {}),
                    # fixed keys LAST: caller metadata can annotate a
                    # generation but never forge its number or shards
                    "format": MANIFEST_FORMAT,
                    "generation": gen,
                    "shards": shards,
                    "landed_at": time.time(),
                }
                tmp = f"{self.path}.tmp.{os.getpid()}.{threading.get_ident()}"
                with open(tmp, "w") as fh:
                    for r in recs:
                        fh.write(json.dumps(r) + "\n")
                    fh.write(json.dumps(rec) + "\n")
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, self.path)
                return gen
            finally:
                lock_fh.close()  # closing drops the flock
