"""The pipeline coordinator: the reference's bastion as a control loop.

The source platform is DRIVEN from outside the cluster — a bastion host
sequences Spark ETL, parameter-server training, and artifact handling
(PAPER.md L3–L7). This module is that role made first-party: a jax-free
control loop that runs **rounds** of

    ingest  →  train  →  export  →  publish

where each stage is a plain callable (the local in-process stage set
lives in :mod:`pyspark_tf_gke_tpu.pipeline.stages`; a production
deployment can swap any stage for a k8s-Job launcher without touching
the loop). The loop owns exactly the concerns a bastion script always
grows by hand, done properly once:

* **crash resume** — after every stage the coordinator persists a state
  file (atomic tmp+fsync+rename, same contract as the shard manifest);
  a restarted coordinator resumes at the first unfinished stage of the
  interrupted round instead of re-ingesting/re-training work that
  already landed;
* **per-stage retry** — transient stage failures ride the shared
  ``retry_with_backoff`` policy (events + ``retries_total{op}``), and a
  stage that exhausts its retries stops the loop with the state file
  still pointing at it;
* **observability** — ``pipeline_rounds_total``,
  ``pipeline_stage_seconds{stage}``, ``pipeline_bundle_generation``,
  and ``pipeline_freshness_seconds`` (data-landed → serving-traffic
  latency, the loop's end-to-end SLO) on the shared registry, plus
  ``pipeline_*`` events on the trail;
* **SIGTERM drain** — :meth:`PipelineCoordinator.request_stop` finishes
  the current stage, persists state, and exits 0 (the k8s rolling-
  restart contract; the next pod resumes from the state file).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Mapping, Optional, Sequence

from pyspark_tf_gke_tpu.obs.events import get_event_log
from pyspark_tf_gke_tpu.obs.metrics import platform_families
from pyspark_tf_gke_tpu.obs.trace import TraceRecorder, use_span
from pyspark_tf_gke_tpu.pipeline.manifest import write_atomic_json
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("pipeline.coordinator")

STAGES = ("ingest", "train", "export", "publish")
STATE_FORMAT = "pyspark_tf_gke_tpu.pipeline_state.v1"


class StageFailed(RuntimeError):
    """A stage exhausted its retries; ``stage`` names it and the state
    file still points at it, so the next coordinator run re-enters the
    round exactly there."""

    def __init__(self, stage: str, cause: BaseException):
        super().__init__(f"stage {stage!r} failed: "
                         f"{type(cause).__name__}: {cause}")
        self.stage = stage
        self.cause = cause


class PipelineState:
    """The coordinator's durable resume point.

    ``round`` is the 1-based round in progress (or about to start);
    ``stage_index`` the next stage to run within it; ``outputs`` the
    completed stages' return dicts for the CURRENT round (inputs to the
    later stages — e.g. export's bundle dir feeds publish);
    ``completed_rounds`` / ``bundle_generation`` are the loop's
    cumulative progress. Everything JSON-serializable by construction.
    """

    def __init__(self, path: str):
        self.path = str(path)
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        self.round = 1
        self.stage_index = 0
        self.outputs: Dict[str, dict] = {}
        # cross-round durable scratch (e.g. the train stage's consumed-
        # batches stream offset) — NOT reset when a round completes
        self.extra: Dict[str, dict] = {}
        self.completed_rounds = 0
        self.bundle_generation = 0
        self.load()

    def load(self) -> bool:
        import json

        try:
            with open(self.path) as fh:
                data = json.load(fh)
        except (FileNotFoundError, ValueError):
            return False
        self.round = int(data.get("round", 1))
        self.stage_index = int(data.get("stage_index", 0))
        self.outputs = dict(data.get("outputs", {}))
        self.extra = dict(data.get("extra", {}))
        self.completed_rounds = int(data.get("completed_rounds", 0))
        self.bundle_generation = int(data.get("bundle_generation", 0))
        return True

    def save(self) -> None:
        write_atomic_json(self.path, {
            "format": STATE_FORMAT,
            "round": self.round,
            "stage_index": self.stage_index,
            "outputs": self.outputs,
            "extra": self.extra,
            "completed_rounds": self.completed_rounds,
            "bundle_generation": self.bundle_generation,
            "updated_at": time.time(),
        })


class PipelineCoordinator:
    """Drives ingest→train→export→publish rounds with durable resume.

    ``stages`` maps each name in :data:`STAGES` to a callable
    ``stage(state: PipelineState, outputs: dict) -> dict`` where
    ``outputs`` holds the current round's completed stage results and
    the return dict becomes ``outputs[name]``. Stage callables must be
    idempotent at round granularity (re-running a completed-then-
    crashed-before-save stage must be safe) — the local stage set is.
    """

    def __init__(self, stages: Mapping[str, Callable],
                 state_path: str,
                 rounds: int = 0,
                 interval_s: float = 0.0,
                 stage_attempts: int = 3,
                 retry_base_delay_s: float = 0.5,
                 heartbeat=None,
                 obs=None, event_log=None, tracer=None):
        missing = [s for s in STAGES if s not in stages]
        if missing:
            raise ValueError(f"stage map is missing {missing}")
        self.stages = dict(stages)
        self.state = PipelineState(state_path)
        self.rounds = int(rounds)  # 0 = run until stopped
        self.interval_s = float(interval_s)
        self.stage_attempts = int(stage_attempts)
        self.retry_base_delay_s = float(retry_base_delay_s)
        self.heartbeat = heartbeat  # train.resilience.Heartbeat
        self._obs = obs if obs is not None else platform_families()
        self._event_log = (event_log if event_log is not None
                           else get_event_log())
        # round-level lineage: ONE trace per round (rounds are rare —
        # sample everything), a child span per stage, and the trace id
        # stamped into the ingest manifest meta + the exported bundle's
        # extra_meta, so a serving generation links back to the round
        # that produced it (the stages read it off the contextvar)
        self.tracer = (tracer if tracer is not None
                       else TraceRecorder(sample=1.0))
        self._stop = threading.Event()
        self._beats = 0

    # -- lifecycle -------------------------------------------------------

    def request_stop(self) -> None:
        """SIGTERM drain: finish the stage in flight, persist state,
        return from :meth:`run` cleanly. Idempotent."""
        self._stop.set()

    @property
    def stopping(self) -> bool:
        return self._stop.is_set()

    def _beat(self) -> None:
        self._beats += 1
        if self.heartbeat is not None:
            try:
                self.heartbeat.beat(self._beats, force=True)
            except OSError:
                pass  # liveness must never take the loop down

    # -- the loop --------------------------------------------------------

    def _run_stage(self, name: str, parent=None) -> dict:
        from pyspark_tf_gke_tpu.train.resilience import retry_with_backoff

        fn = self.stages[name]
        t0 = time.perf_counter()
        self._event_log.emit("pipeline_stage_start", stage=name,
                             round=self.state.round)
        span = self.tracer.start_span(f"pipeline.{name}", parent=parent,
                                      attrs={"round": self.state.round})
        try:
            with use_span(span):
                out = retry_with_backoff(
                    lambda: fn(self.state, dict(self.state.outputs)),
                    attempts=self.stage_attempts,
                    base_delay_s=self.retry_base_delay_s,
                    op=f"pipeline_{name}")
        except Exception as exc:  # noqa: BLE001 — surfaced typed below
            span.finish(status=f"error:{type(exc).__name__}")
            self._obs["pipeline_stage_failures_total"].labels(
                stage=name).inc()
            self._event_log.emit(
                "pipeline_stage_failed", stage=name,
                round=self.state.round,
                error=f"{type(exc).__name__}: {exc}"[:500])
            raise StageFailed(name, exc) from exc
        span.finish(status="ok")
        dt = time.perf_counter() - t0
        self._obs["pipeline_stage_seconds"].labels(stage=name).observe(dt)
        self._event_log.emit("pipeline_stage_end", stage=name,
                             round=self.state.round,
                             seconds=round(dt, 3))
        return out if isinstance(out, dict) else {}

    def run_round(self) -> None:
        """Run the current round from its resume point; advances the
        state file after every stage. Raises :class:`StageFailed` with
        the state still pointing at the failed stage. The whole round
        rides ONE trace (``pipeline.round``) with a child span per
        stage; a resumed round opens a fresh trace for the remaining
        stages (the ids differ, the manifest/bundle stamps came from
        the round that actually ran the stage)."""
        round_span = None
        if self.state.stage_index < len(STAGES):
            round_span = self.tracer.start_span(
                "pipeline.round", attrs={"round": self.state.round})
        try:
            while self.state.stage_index < len(STAGES):
                name = STAGES[self.state.stage_index]
                self._beat()
                out = self._run_stage(name, parent=round_span)
                self.state.outputs[name] = out
                self.state.stage_index += 1
                if name == "publish":
                    gen = int(out.get("generation",
                                      self.state.bundle_generation))
                    if out.get("published"):
                        self.state.bundle_generation = gen
                        self._obs["pipeline_bundle_generation"].set(gen)
                        landed = (self.state.outputs.get("ingest")
                                  or {}).get("landed_at")
                        if landed:
                            fresh = max(0.0, time.time() - float(landed))
                            self._obs["pipeline_freshness_seconds"].set(
                                fresh)
                            self._event_log.emit(
                                "pipeline_published",
                                round=self.state.round,
                                generation=gen,
                                freshness_s=round(fresh, 3))
                self.state.save()
        finally:
            if round_span is not None:
                round_span.finish()
        # round complete: reset for the next one
        self.state.completed_rounds += 1
        self.state.round += 1
        self.state.stage_index = 0
        self.state.outputs = {}
        self.state.save()
        self._obs["pipeline_rounds_total"].inc()
        self._event_log.emit("pipeline_round_end",
                             completed=self.state.completed_rounds)

    def run(self) -> int:
        """Round loop until ``rounds`` complete (0 = forever) or a stop
        is requested. Returns 0 on clean exit/drain; raises
        :class:`StageFailed` when a stage exhausts its retries."""
        # a crash between the post-publish save and the round-complete
        # save persists stage_index == len(STAGES); run_round's loop
        # handles it (falls straight to round completion) — the resume
        # label must not index past the stage list
        i = self.state.stage_index
        self._event_log.emit(
            "pipeline_started", resume_round=self.state.round,
            resume_stage=(STAGES[i] if i < len(STAGES)
                          else "round-complete"),
            completed_rounds=self.state.completed_rounds)
        while not self._stop.is_set():
            if self.rounds and self.state.completed_rounds >= self.rounds:
                break
            self.run_round()
            if self.interval_s and not self._stop.is_set():
                # interruptible sleep between rounds (SIGTERM-prompt)
                self._stop.wait(self.interval_s)
        self._event_log.emit(
            "pipeline_stopped", completed_rounds=self.state.completed_rounds,
            drained=self._stop.is_set())
        return 0


def resolve_replicas(spec: str) -> Sequence[str]:
    """Expand a ``--replicas`` spec into base URLs.

    Comma-separated entries; each is either a literal ``http://host:port``
    or ``dns://name:port`` — resolved to one URL per A record, the same
    headless-Service convention the router's discovery uses (each serve
    pod must be addressed INDIVIDUALLY for a rolling publish)."""
    import socket

    out = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        if entry.startswith("dns://"):
            hostport = entry[len("dns://"):]
            host, _, port = hostport.partition(":")
            port = int(port or 8000)
            try:
                infos = socket.getaddrinfo(host, port, proto=socket.IPPROTO_TCP)
            except OSError as exc:
                raise ValueError(f"cannot resolve {entry!r}: {exc}") from exc
            addrs = sorted({info[4][0] for info in infos})
            out.extend(f"http://{a}:{port}" for a in addrs)
        else:
            out.append(entry.rstrip("/"))
    return out
