"""Fleet publish: rolling bundle hot-swap across serving replicas.

The serving side owns the heavy machinery (off-driver load, compat
checks, canary, rollback — ``train/serve.py`` ``reload_bundle``); this
module is the coordinator's thin, jax-free client for it:

* :func:`reload_replica` — one ``POST /admin/reload`` (token via the
  ``X-Admin-Token`` header) returning the replica's verdict;
* :func:`confirm_generation` — poll ``GET /loadz`` until
  ``bundle_generation`` reaches the target (the same signal the
  router's prober reads, so "confirmed" == "the router can see it");
* :func:`rolling_publish` — batches of at most ``max_unavailable``
  replicas reload concurrently; each batch must confirm before the
  next starts, and ANY failure stops the rollout — at least
  ``N - max_unavailable`` replicas are serving (old or new generation,
  never broken: a failed reload rolls back server-side) at every
  moment of the rollout.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import List, Optional, Sequence

from pyspark_tf_gke_tpu.chaos.inject import chaos_fire
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("pipeline.publish")


def _read_json(resp) -> dict:
    try:
        return json.loads(resp.read().decode())
    except (ValueError, UnicodeDecodeError):
        return {}


def reload_replica(base_url: str, bundle_dir: str, generation: int,
                   token: str = "", canary: bool = True,
                   timeout_s: float = 120.0) -> dict:
    """POST /admin/reload on one replica. Returns
    ``{"ok": bool, "status": int, "body": dict}`` — transport errors
    and HTTP error statuses both land as ``ok=False`` with the body the
    replica sent (the rollback verdict rides it)."""
    payload = {"bundle": bundle_dir, "generation": int(generation),
               "canary": bool(canary)}
    req = urllib.request.Request(
        base_url.rstrip("/") + "/admin/reload",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 **({"X-Admin-Token": token} if token else {})})
    try:
        # chaos: the publish fault point, INSIDE the try — an injected
        # failure lands as ok=False exactly like a transport failure,
        # so rolling_publish's stop-the-rollout and the coordinator's
        # resume-at-the-publish-stage run their REAL paths
        chaos_fire("pipeline.publish", replica=base_url)
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return {"ok": True, "status": resp.status,
                    "body": _read_json(resp)}
    except urllib.error.HTTPError as exc:
        body = _read_json(exc)
        return {"ok": False, "status": exc.code, "body": body}
    except Exception as exc:  # noqa: BLE001 — transport failure
        return {"ok": False, "status": 0,
                "body": {"error": f"{type(exc).__name__}: {exc}"}}


def confirm_generation(base_url: str, generation: int,
                       timeout_s: float = 60.0,
                       poll_s: float = 0.25) -> bool:
    """Poll /loadz until the replica advertises ``bundle_generation >=
    generation`` and is not draining. The generation only advances
    after a successful canary, so True means the new bundle is
    SERVING, not merely loaded."""
    deadline = time.monotonic() + float(timeout_s)
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                    base_url.rstrip("/") + "/loadz", timeout=5) as resp:
                load = _read_json(resp)
            if (int(load.get("bundle_generation") or 0) >= int(generation)
                    and not load.get("draining")):
                return True
        except Exception:  # noqa: BLE001 — mid-swap blip: keep polling
            pass
        time.sleep(poll_s)
    return False


def rolling_publish(replicas: Sequence[str], bundle_dir: str,
                    generation: int, token: str = "",
                    max_unavailable: int = 1,
                    confirm_timeout_s: float = 60.0,
                    canary: bool = True,
                    reload_timeout_s: float = 120.0) -> dict:
    """Hot-swap ``bundle_dir`` across the fleet, at most
    ``max_unavailable`` replicas at a time.

    Returns ``{"ok", "published", "generation", "results"}`` where
    ``results`` is one entry per replica attempted (replicas after a
    failed batch are NOT attempted — they keep serving the old
    generation). A replica counts as published only after
    :func:`confirm_generation` sees the new generation live."""
    import threading

    replicas = [r.rstrip("/") for r in replicas]
    max_unavailable = max(1, int(max_unavailable))
    results: List[dict] = []
    published = 0
    ok = True
    for i in range(0, len(replicas), max_unavailable):
        batch = replicas[i:i + max_unavailable]
        batch_results: List[Optional[dict]] = [None] * len(batch)

        def one(j: int, url: str) -> None:
            out = reload_replica(url, bundle_dir, generation,
                                 token=token, canary=canary,
                                 timeout_s=reload_timeout_s)
            if out["ok"] and not confirm_generation(
                    url, generation, timeout_s=confirm_timeout_s):
                out = {**out, "ok": False,
                       "body": {**out.get("body", {}),
                                "error": "generation never confirmed "
                                         "on /loadz"}}
            batch_results[j] = {"replica": url, **out}

        threads = [threading.Thread(target=one, args=(j, url),
                                    name=f"publish-{url}")
                   for j, url in enumerate(batch)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for res in batch_results:
            results.append(res)
            if res["ok"]:
                published += 1
                logger.info("published generation %d to %s",
                            generation, res["replica"])
            else:
                ok = False
                logger.error("publish FAILED on %s: %s", res["replica"],
                             res["body"])
        if not ok:
            break  # stop the rollout; untouched replicas keep serving
    return {"ok": ok, "published": published,
            "generation": int(generation), "results": results}
