"""Continuous pipeline plane: the coordinator-driven ETL→train→publish
loop that turns the three batch-shaped planes (``etl/`` feature
pipelines, the trainer, the router+BundleServer serving fleet) into one
demonstrable system — the reference platform's bastion role,
implemented as a first-party control loop (docs/PIPELINE.md).

Jax-free by the platform's convention (like ``router/``): the
coordinator makes no device calls — the local stage set lazy-imports
the data/train planes inside stage bodies, and a production deployment
swaps those for k8s-Job launchers.

Entry point: ``python -m pyspark_tf_gke_tpu.pipeline`` (the
``infra/k8s/tpu/tpu-pipeline.yaml`` Deployment runs it on CPU nodes,
bastion-style).
"""

from pyspark_tf_gke_tpu.pipeline.coordinator import (
    STAGES,
    PipelineCoordinator,
    PipelineState,
    StageFailed,
    resolve_replicas,
)
from pyspark_tf_gke_tpu.pipeline.manifest import (
    ShardSetManifest,
    write_atomic_json,
)
from pyspark_tf_gke_tpu.pipeline.publish import (
    confirm_generation,
    reload_replica,
    rolling_publish,
)
from pyspark_tf_gke_tpu.pipeline.stages import (
    LocalPipelineConfig,
    make_local_stages,
)

__all__ = [
    "STAGES",
    "PipelineCoordinator",
    "PipelineState",
    "StageFailed",
    "ShardSetManifest",
    "LocalPipelineConfig",
    "make_local_stages",
    "resolve_replicas",
    "reload_replica",
    "confirm_generation",
    "rolling_publish",
    "write_atomic_json",
]
