"""``python -m pyspark_tf_gke_tpu.pipeline`` — run the continuous
ETL→train→export→publish loop (docs/PIPELINE.md).

The flags/env mirror the serve CLI's conventions; the admin token for
the fleet's ``POST /admin/reload`` endpoints comes from
``SERVE_ADMIN_TOKEN`` (env only — a token on the command line would
leak into ``ps`` output and pod specs)."""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from pyspark_tf_gke_tpu.pipeline.coordinator import (
    PipelineCoordinator,
    StageFailed,
)
from pyspark_tf_gke_tpu.pipeline.stages import (
    LocalPipelineConfig,
    make_local_stages,
)
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("pipeline.main")


def parse_args(argv=None) -> argparse.Namespace:
    e = os.environ.get
    p = argparse.ArgumentParser(
        description="Continuous ETL->train->export->publish coordinator")
    p.add_argument("--work-dir", default=e("PIPELINE_WORK_DIR", ""),
                   required=not e("PIPELINE_WORK_DIR"),
                   help="root for shards/, checkpoints/, bundles/ and "
                        "the state file")
    p.add_argument("--rounds", type=int,
                   default=int(e("PIPELINE_ROUNDS", "0")),
                   help="rounds to run before exiting (0 = run until "
                        "SIGTERM)")
    p.add_argument("--interval", type=float,
                   default=float(e("PIPELINE_INTERVAL", "0")),
                   help="seconds to sleep between rounds (0 = "
                        "back-to-back); the sleep is SIGTERM-interruptible")
    p.add_argument("--rows-per-round", type=int,
                   default=int(e("PIPELINE_ROWS_PER_ROUND", "2048")))
    p.add_argument("--seq-len", type=int,
                   default=int(e("PIPELINE_SEQ_LEN", "64")))
    p.add_argument("--num-shards", type=int,
                   default=int(e("PIPELINE_NUM_SHARDS", "4")))
    p.add_argument("--steps-per-round", type=int,
                   default=int(e("PIPELINE_STEPS_PER_ROUND", "8")))
    p.add_argument("--batch-size", type=int,
                   default=int(e("PIPELINE_BATCH_SIZE", "8")))
    p.add_argument("--learning-rate", type=float,
                   default=float(e("PIPELINE_LEARNING_RATE", "1e-3")))
    p.add_argument("--hidden-size", type=int,
                   default=int(e("PIPELINE_HIDDEN_SIZE", "32")))
    p.add_argument("--num-layers", type=int,
                   default=int(e("PIPELINE_NUM_LAYERS", "2")))
    p.add_argument("--num-heads", type=int,
                   default=int(e("PIPELINE_NUM_HEADS", "2")))
    p.add_argument("--intermediate-size", type=int,
                   default=int(e("PIPELINE_INTERMEDIATE_SIZE", "64")))
    p.add_argument("--tokenizer", default=e("PIPELINE_TOKENIZER", "byte"))
    p.add_argument("--quantize", action="store_true",
                   default=e("PIPELINE_QUANTIZE", "") == "1",
                   help="export int8 weight-quantized bundles")
    p.add_argument("--bundle-url-prefix",
                   default=e("PIPELINE_BUNDLE_URL_PREFIX", ""),
                   help="how REPLICAS address published bundles when "
                        "that differs from the coordinator's local "
                        "path (work dir on a GCS FUSE mount, fleet "
                        "pulling gs:// URLs): the published bundle's "
                        "basename is appended to this prefix")
    p.add_argument("--replicas", default=e("PIPELINE_REPLICAS", ""),
                   help="comma-separated serving replicas to hot-swap "
                        "published bundles into: http://host:port "
                        "entries and/or dns://service:port (headless "
                        "Service, one replica per A record). Empty = "
                        "bundles land on disk only")
    p.add_argument("--max-unavailable", type=int,
                   default=int(e("PIPELINE_MAX_UNAVAILABLE", "1")),
                   help="replicas reloading concurrently during a "
                        "rolling publish")
    p.add_argument("--confirm-timeout", type=float,
                   default=float(e("PIPELINE_CONFIRM_TIMEOUT", "60")),
                   help="seconds to wait for /loadz to advertise the "
                        "new bundle_generation per replica")
    p.add_argument("--no-canary", action="store_true",
                   default=e("PIPELINE_NO_CANARY", "") == "1",
                   help="skip the replicas' post-swap canary generate "
                        "(NOT recommended: canary failure is what "
                        "triggers server-side rollback)")
    p.add_argument("--stage-attempts", type=int,
                   default=int(e("PIPELINE_STAGE_ATTEMPTS", "3")))
    p.add_argument("--state-file", default=e("PIPELINE_STATE_FILE", ""),
                   help="crash-resume state path (default "
                        "WORK_DIR/pipeline_state.json)")
    p.add_argument("--heartbeat-file", default=e("HEARTBEAT_FILE", ""),
                   help="node-local liveness file beaten once per stage "
                        "(k8s exec probe watches its age)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    cfg = LocalPipelineConfig(
        work_dir=args.work_dir,
        rows_per_round=args.rows_per_round,
        seq_len=args.seq_len,
        num_shards=args.num_shards,
        tokenizer=args.tokenizer,
        steps_per_round=args.steps_per_round,
        batch_size=args.batch_size,
        learning_rate=args.learning_rate,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_heads=args.num_heads,
        intermediate_size=args.intermediate_size,
        quantize=args.quantize,
        # raw entries (dns:// included): the publish stage re-resolves
        # every round, so the rollout tracks the live fleet
        replicas=tuple(e.strip() for e in args.replicas.split(",")
                       if e.strip()),
        admin_token=os.environ.get("SERVE_ADMIN_TOKEN", ""),
        max_unavailable=args.max_unavailable,
        confirm_timeout_s=args.confirm_timeout,
        canary=not args.no_canary,
        bundle_url_prefix=args.bundle_url_prefix,
    )
    heartbeat = None
    if args.heartbeat_file:
        from pyspark_tf_gke_tpu.train.resilience import Heartbeat

        heartbeat = Heartbeat(args.heartbeat_file, every_steps=1)
    coord = PipelineCoordinator(
        make_local_stages(cfg),
        state_path=(args.state_file
                    or os.path.join(args.work_dir, "pipeline_state.json")),
        rounds=args.rounds,
        interval_s=args.interval,
        stage_attempts=args.stage_attempts,
        heartbeat=heartbeat)

    if threading.current_thread() is threading.main_thread():
        # SIGTERM drain: finish the stage in flight, persist state,
        # exit 0 — the replacement pod resumes from the state file
        signal.signal(signal.SIGTERM,
                      lambda signum, frame: coord.request_stop())
    try:
        return coord.run()
    except StageFailed as exc:
        logger.error("pipeline stopped: %s (state file points at the "
                     "failed stage; restart resumes there)", exc)
        return 1


if __name__ == "__main__":
    sys.exit(main())
