"""Training state pytree.

One flat struct holding params, optimizer state, optional batch-norm
statistics, and the step counter. In the reference this state lived
*physically* on parameter servers and was mutated asynchronously over gRPC
(``train_tf_ps.py:611-647``); here it is a pure pytree, sharded across the
mesh by ``NamedSharding`` and threaded functionally through the jitted
step (donated, so XLA updates it in place).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    batch_stats: Any = None

    def apply_gradients(self, grads: Any, **updates) -> "TrainState":
        updates_tx, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates_tx)
        return self.replace(
            step=self.step + 1, params=new_params, opt_state=new_opt_state, **updates
        )

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation,
               batch_stats: Any = None) -> "TrainState":
        import jax.numpy as jnp

        return cls(
            step=jnp.zeros((), dtype=jnp.int32),
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats,
            tx=tx,
        )
