"""Training state pytree.

One flat struct holding params, optimizer state, optional batch-norm
statistics, and the step counter. In the reference this state lived
*physically* on parameter servers and was mutated asynchronously over gRPC
(``train_tf_ps.py:611-647``); here it is a pure pytree, sharded across the
mesh by ``NamedSharding`` and threaded functionally through the jitted
step (donated, so XLA updates it in place).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    batch_stats: Any = None
    # Exponential moving average of params (None = disabled). The decay
    # is a static hyperparameter; ema_params shard exactly like params.
    ema_params: Any = None
    ema_decay: float = struct.field(pytree_node=False, default=0.0)

    def apply_gradients(self, grads: Any, **updates) -> "TrainState":
        updates_tx, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates_tx)
        if self.ema_params is not None:
            d = self.ema_decay
            updates.setdefault("ema_params", jax.tree.map(
                lambda e, p: d * e + (1.0 - d) * p, self.ema_params, new_params))
        return self.replace(
            step=self.step + 1, params=new_params, opt_state=new_opt_state, **updates
        )

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation,
               batch_stats: Any = None, ema_decay: float = 0.0) -> "TrainState":
        import jax.numpy as jnp

        if not 0.0 <= ema_decay < 1.0:
            # decay == 1 would freeze the EMA at init forever (and the
            # export path prefers EMA weights) — reject it loudly.
            raise ValueError(f"ema_decay must be in [0, 1), got {ema_decay}")
        return cls(
            step=jnp.zeros((), dtype=jnp.int32),
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats,
            ema_params=jax.tree.map(jnp.copy, params) if ema_decay else None,
            ema_decay=ema_decay,
            tx=tx,
        )
