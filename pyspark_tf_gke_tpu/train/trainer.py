"""The trainer: sharded jit train step + epoch loop.

Replaces the reference's ParameterServerStrategy machinery
(``train_tf_ps.py:440-511``) and its coordinator-scheduled step loop
(``train_tf_ps.py:611-647``) with the SPMD design (SURVEY §7): one jitted
``train_step`` — forward, loss, grad, Adam update — compiled once over a
device mesh. Gradient combination across chips is *implicit*: the batch is
sharded over the data axes, so XLA inserts the allreduce over ICI.
Parameter sharding (the ``MinSizePartitioner`` analog) is a
``NamedSharding`` on the state pytree, applied identically to params and
optimizer moments.

Training here is **synchronous** data-parallel by design — the reference's
asynchronous PS updates are an artifact of its gRPC push/pull transport;
on a TPU mesh synchronous allreduce is both faster and better-behaved
(loss parity at worker-count>1 is therefore final-metric parity, per
BASELINE.md).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pyspark_tf_gke_tpu.obs.events import get_event_log
from pyspark_tf_gke_tpu.obs.metrics import get_registry, platform_families
from pyspark_tf_gke_tpu.parallel.mesh import batch_sharding
from pyspark_tf_gke_tpu.parallel.sharding import (
    DEFAULT_MIN_SIZE,
    LOGICAL_RULES,
    fsdp_spec,
)
from pyspark_tf_gke_tpu.train.losses import (
    accuracy_metric,
    mae_metric,
    mse_loss,
    softmax_cross_entropy,
)
from pyspark_tf_gke_tpu.train.state import TrainState
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("train.trainer")

# Weight on the MoE load-balance auxiliary loss (Switch Transformer's 1e-2).
MOE_AUX_WEIGHT = 0.01


@dataclasses.dataclass(frozen=True)
class TrainerTask:
    """How a model family plugs into the generic step: how to call it and
    how to score it. The ``(preds, batch) -> (loss, metrics)`` pairings
    mirror the reference's compile() choices (train_tf_ps.py:336-377)."""

    name: str
    forward: Callable[..., Any]  # (model, variables, batch, train, mutable) -> (preds, new_model_state|None)
    loss_and_metrics: Callable[[Any, Dict[str, jnp.ndarray]], Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]
    has_batch_stats: bool = False


def _forward_simple(model, variables, batch, train, mutable):
    return model(variables, batch), None


def classification_task() -> TrainerTask:
    def forward(model, variables, batch, train, mutable):
        return model.apply(variables, batch["x"]), None

    def lam(preds, batch):
        loss = softmax_cross_entropy(preds, batch["y"])
        return loss, {"loss": loss, "accuracy": accuracy_metric(preds, batch["y"])}

    return TrainerTask("classification", forward, lam)


def regression_task() -> TrainerTask:
    def forward(model, variables, batch, train, mutable):
        return model.apply(variables, batch["image"]), None

    def lam(preds, batch):
        loss = mse_loss(preds, batch["target"])
        return loss, {
            "loss": loss,
            "mse": loss,
            "mae": mae_metric(preds, batch["target"]),
        }

    return TrainerTask("regression", forward, lam)


def _image_cls_lam(preds, batch):
    loss = softmax_cross_entropy(preds, batch["label"])
    return loss, {"loss": loss, "accuracy": accuracy_metric(preds, batch["label"])}


def resnet_task() -> TrainerTask:
    def forward(model, variables, batch, train, mutable):
        if train:
            preds, new_state = model.apply(
                variables, batch["image"], train=True, mutable=["batch_stats"]
            )
            # Stat-free norm variants (gn/none diagnostics) yield no
            # mutable collection; mirror init_state's None so the scan
            # carry keeps one pytree structure either way.
            return preds, new_state.get("batch_stats")
        return model.apply(variables, batch["image"], train=False), None

    return TrainerTask("resnet", forward, _image_cls_lam, has_batch_stats=True)


def vit_task() -> TrainerTask:
    """Image classification for stateless transformer classifiers
    (models/vit.py — no batch-norm statistics to thread; dict preds
    carry the MoE aux loss when experts are enabled)."""

    def forward(model, variables, batch, train, mutable):
        return model.apply(variables, batch["image"]), None

    def lam(preds, batch):
        loss, metrics = _image_cls_lam(preds["logits"], batch)
        return _add_moe_aux(loss, metrics, preds)

    return TrainerTask("vit", forward, lam)


def _bert_forward(model, variables, batch, train, mutable):
    """Shared forward for every BERT objective (classification, MLM).
    ``train`` routes the embedding lookup: one-hot matmul when a
    gradient will flow, plain gather for eval (models/embedding.py)."""
    return model.apply(
        variables, batch["input_ids"],
        attention_mask=batch.get("attention_mask"), train=train
    ), None


def _add_moe_aux(loss, metrics, preds):
    """MoE load-balance loss (models/moe.py); 0 for dense configs."""
    aux = preds.get("aux_loss")
    if aux is not None:
        loss = loss + MOE_AUX_WEIGHT * aux
        metrics["moe_aux_loss"] = aux
    return loss, metrics


def bert_classification_task() -> TrainerTask:
    def lam(preds, batch):
        logits = preds["cls_logits"]
        loss = softmax_cross_entropy(logits, batch["labels"])
        metrics = {"loss": loss, "accuracy": accuracy_metric(logits, batch["labels"])}
        return _add_moe_aux(loss, metrics, preds)

    return TrainerTask("bert_classification", _bert_forward, lam)


def bert_mlm_task() -> TrainerTask:
    """Masked-language-model pretraining: cross-entropy over the masked
    positions only (labels == IGNORE_INDEX elsewhere — data/mlm.py)."""
    from pyspark_tf_gke_tpu.data.mlm import IGNORE_INDEX

    def lam(preds, batch):
        logits = preds["mlm_logits"].astype(jnp.float32)  # [B, S, V]
        labels = batch["mlm_labels"]
        mask = (labels != IGNORE_INDEX)
        safe = jnp.where(mask, labels, 0)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
        denom = jnp.maximum(mask.sum(), 1)
        loss = jnp.where(mask, per_tok, 0.0).sum() / denom
        acc = (jnp.where(mask, jnp.argmax(logits, -1) == safe, False).sum()
               / denom)
        metrics = {"loss": loss, "mlm_accuracy": acc,
                   "masked_frac": mask.mean()}
        return _add_moe_aux(loss, metrics, preds)

    return TrainerTask("bert_mlm", _bert_forward, lam)


def causal_lm_task(vocab_chunks: Optional[int] = None) -> TrainerTask:
    """Next-token prediction: shift-by-one cross entropy over every
    position that has a successor (optionally masked by attention_mask).

    ``vocab_chunks=N`` switches to the chunked large-vocab loss
    (``ops/chunked_ce.py``): the model returns final hidden states and
    the LM-head weight is applied chunk-by-chunk inside the loss, so the
    fp32 ``[B, S, V]`` logits — the memory hog of LM training — never
    materialize. Numerics match the dense path to fp32 tolerance."""

    def _reduce(per_tok, pred_ids, targets, mask):
        if mask is not None:
            m = mask[:, 1:].astype(jnp.float32)
            denom = jnp.maximum(m.sum(), 1.0)
            loss = (per_tok * m).sum() / denom
            acc = ((pred_ids == targets) * m).sum() / denom
        else:
            loss = per_tok.mean()
            acc = (pred_ids == targets).astype(jnp.float32).mean()
        return loss, {"loss": loss, "next_token_accuracy": acc}

    if vocab_chunks:
        from pyspark_tf_gke_tpu.ops.chunked_ce import chunked_cross_entropy

        def forward(model, variables, batch, train, mutable):
            hidden = model.apply(variables, batch["input_ids"],
                                 segment_ids=batch.get("segment_ids"),
                                 return_hidden=True, train=train)
            head = variables["params"]["lm_head"]
            return {"hidden": hidden, "kernel": head["kernel"],
                    "bias": head.get("bias")}, None

        def lam(preds, batch):
            ids = batch["input_ids"]
            targets = ids[:, 1:]
            h = preds["hidden"][:, :-1]
            b, s1, e = h.shape
            per_tok, amax = chunked_cross_entropy(
                h.reshape(b * s1, e), preds["kernel"], preds["bias"],
                targets.reshape(-1), num_chunks=vocab_chunks)
            return _reduce(per_tok.reshape(b, s1),
                           amax.reshape(b, s1), targets,
                           batch.get("attention_mask"))

        return TrainerTask("causal_lm", forward, lam)

    def forward(model, variables, batch, train, mutable):
        return model.apply(variables, batch["input_ids"],
                           segment_ids=batch.get("segment_ids"),
                           train=train), None

    def lam(logits, batch):
        ids = batch["input_ids"]
        targets = ids[:, 1:]
        lg = logits[:, :-1].astype(jnp.float32)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(lg, targets)
        return _reduce(per_tok, jnp.argmax(lg, -1), targets,
                       batch.get("attention_mask"))

    return TrainerTask("causal_lm", forward, lam)


TASKS = {
    "classification": classification_task,
    "regression": regression_task,
    "resnet": resnet_task,
    "vit": vit_task,
    "bert_classification": bert_classification_task,
    "bert_mlm": bert_mlm_task,
    "causal_lm": causal_lm_task,
}


class _CountingIterator:
    """Pass-through iterator that tallies consumed global rows (for
    examples/sec accounting across plain and grad-accum steps)."""

    def __init__(self, it):
        self._it = it
        self.rows = 0

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self._it)
        self.rows += next(iter(batch.values())).shape[0]
        return batch


class Trainer:
    """Builds sharded state, compiles the step, runs the epoch loop."""

    def __init__(
        self,
        model: nn.Module,
        task: TrainerTask,
        mesh: Mesh,
        learning_rate: float = 1e-3,
        tx: Optional[optax.GradientTransformation] = None,
        fsdp_min_size: int = DEFAULT_MIN_SIZE,
        logical_rules=LOGICAL_RULES,
        ema_decay: float = 0.0,  # >0 maintains an EMA of params (eval/serving)
        mu_dtype: Optional[Any] = None,  # Adam first-moment dtype; bf16
        # halves that slice of the per-step param/optimizer HBM traffic
        # — the flagship (43M params, batch 32) is bound on exactly that
        # stream (tools/roofline.py analytic model). Default f32 keeps
        # reference-parity optimizer numerics; ignored when tx is given.
        metrics_registry=None,  # obs.MetricsRegistry (default: shared)
        event_log=None,  # obs.EventLog (default: shared trail)
    ):
        self.model = model
        self.task = task
        self.mesh = mesh
        self.tx = tx if tx is not None else optax.adam(
            learning_rate, mu_dtype=mu_dtype)
        self.fsdp_min_size = fsdp_min_size
        self.logical_rules = logical_rules
        self.ema_decay = ema_decay
        self._train_step = None
        self._raw_train_step = None
        self._eval_step = None
        self._debug_step = None
        self._grad_step = None
        self._accum_add = None
        self._apply_step = None
        self._scan_steps: Dict[int, Any] = {}
        self.state_shardings = None
        # observability plane (obs/): history stays the artifact format;
        # these are the live/scrapable view of the same loop
        self.metrics_registry = (metrics_registry if metrics_registry
                                 is not None else get_registry())
        self._obs = platform_families(self.metrics_registry)
        self._event_log = event_log if event_log is not None else get_event_log()

    # ---- state construction -------------------------------------------------

    def _sample_inputs(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Minimal batch slice for shape-only init: one row per data-parallel
        shard (shard_map paths, e.g. ring attention, need the global batch
        divisible by dp*fsdp even at init). Batches with fewer rows than
        shards — legitimate on multi-host, where the local batch can be
        smaller than the global shard count — are tiled up; this is shape
        tracing only, values are irrelevant."""
        n = self.mesh.shape.get("dp", 1) * self.mesh.shape.get("fsdp", 1)
        rows = len(next(iter(batch.values())))
        if rows < n:
            reps = -(-n // rows)  # ceil
            batch = {k: np.concatenate([np.asarray(v)] * reps) for k, v in batch.items()}
        return {k: v[:n] for k, v in batch.items()}

    def _create_fn(self, sample_batch):
        model, task, tx = self.model, self.task, self.tx

        def create(rng):
            if task.name == "resnet":
                variables = model.init(rng, sample_batch["image"], train=False)
            elif task.name == "vit":
                variables = model.init(rng, sample_batch["image"])
            elif task.name.startswith("bert"):
                variables = model.init(
                    rng,
                    sample_batch["input_ids"],
                    attention_mask=sample_batch.get("attention_mask"),
                )
            elif task.name == "causal_lm":
                variables = model.init(rng, sample_batch["input_ids"])
            elif task.name == "regression":
                variables = model.init(rng, sample_batch["image"])
            else:
                variables = model.init(rng, sample_batch["x"])
            params = variables["params"]
            batch_stats = variables.get("batch_stats")
            return TrainState.create(params, tx, batch_stats,
                                     ema_decay=self.ema_decay)

        return create

    def init_state(self, rng: jax.Array, sample_batch: Dict[str, np.ndarray]) -> TrainState:
        """Init params directly into their target shardings (jit with
        out_shardings) so large models never materialize unsharded."""
        sample = self._sample_inputs(sample_batch)
        create = self._create_fn(sample)
        abstract = jax.eval_shape(create, rng)

        boxed = any(
            isinstance(l, nn.Partitioned)
            for l in jax.tree.leaves(
                abstract, is_leaf=lambda x: isinstance(x, nn.Partitioned)
            )
        )
        if boxed:
            specs = nn.get_partition_spec(abstract)
            shardings = nn.logical_to_mesh_sharding(specs, self.mesh, self.logical_rules)

            # Unbox WITHOUT the in-jit constraint (see the shim's
            # docstring — raw-Partitioned LOGICAL names crash strict
            # NamedSharding validation); the jit's ``out_shardings``
            # below is the placement authority either way.
            from pyspark_tf_gke_tpu.parallel.compat import (
                unbox_without_constraint,
            )

            create_unboxed = lambda r: unbox_without_constraint(create(r))
        else:
            shardings = jax.tree.map(
                lambda l: NamedSharding(
                    self.mesh, fsdp_spec(l.shape, self.mesh, self.fsdp_min_size)
                ),
                abstract,
            )
            create_unboxed = create

        self.state_shardings = shardings
        with self.mesh:
            state = jax.jit(create_unboxed, out_shardings=shardings)(rng)
        return state

    # ---- compiled steps -----------------------------------------------------

    def _build_steps(self):
        model, task = self.model, self.task

        def train_step(state: TrainState, batch):
            def loss_fn(params):
                variables = {"params": params}
                if state.batch_stats is not None:
                    variables["batch_stats"] = state.batch_stats
                preds, new_batch_stats = task.forward(model, variables, batch, True, True)
                loss, metrics = task.loss_and_metrics(preds, batch)
                return loss, (metrics, new_batch_stats)

            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
            (_, (metrics, new_batch_stats)), grads = grad_fn(state.params)
            if task.has_batch_stats and new_batch_stats is not None:
                state = state.apply_gradients(grads, batch_stats=new_batch_stats)
            else:
                state = state.apply_gradients(grads)
            return state, metrics

        def eval_step(state: TrainState, batch):
            variables = {"params": state.params}
            if state.batch_stats is not None:
                variables["batch_stats"] = state.batch_stats
            preds, _ = task.forward(model, variables, batch, False, False)
            _, metrics = task.loss_and_metrics(preds, batch)
            return metrics

        self._raw_train_step = train_step
        self._train_step = jax.jit(
            train_step,
            donate_argnums=0,
            out_shardings=(self.state_shardings, None),
        )
        self._eval_step = jax.jit(eval_step)

    def step(self, state: TrainState, batch: Dict[str, jax.Array]):
        if self._train_step is None:
            self._build_steps()
        with self.mesh:
            return self._train_step(state, batch)

    def _build_accum_steps(self):
        """Two-phase step for gradient accumulation: grads-only compute per
        microbatch, one optimizer apply per A microbatches. Emulates an
        A-times-larger global batch with the same device memory."""
        model, task = self.model, self.task

        def grad_step(state: TrainState, batch):
            def loss_fn(params):
                variables = {"params": params}
                if state.batch_stats is not None:
                    variables["batch_stats"] = state.batch_stats
                preds, new_bs = task.forward(model, variables, batch, True, True)
                loss, metrics = task.loss_and_metrics(preds, batch)
                return loss, (metrics, new_bs)

            (_, (metrics, new_bs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            return grads, metrics, new_bs

        def apply_step(state: TrainState, grads, new_batch_stats):
            if task.has_batch_stats and new_batch_stats is not None:
                return state.apply_gradients(grads, batch_stats=new_batch_stats)
            return state.apply_gradients(grads)

        def apply_mean(state: TrainState, grads_sum, bs_sum, accum):
            grads = jax.tree.map(lambda g: g / accum, grads_sum)
            bs = (
                None if bs_sum is None
                else jax.tree.map(lambda b: b / accum, bs_sum)
            )
            return apply_step(state, grads, bs)

        param_shardings = (
            self.state_shardings.params if self.state_shardings is not None else None
        )
        self._grad_step = jax.jit(grad_step, out_shardings=(param_shardings, None, None))
        # One fused add per accumulation round, donating the accumulator —
        # no per-leaf host dispatches and no extra live gradient buffer.
        self._accum_add = jax.jit(
            lambda acc, new: jax.tree.map(jnp.add, acc, new), donate_argnums=0
        )
        # Donate only the state: its buffers back every output 1:1.
        # Donating grads too made XLA warn "donated buffers were not
        # usable" — there is no output left for them to back.
        self._apply_step = jax.jit(
            apply_mean, donate_argnums=0, out_shardings=self.state_shardings
        )

    def accum_step(self, state: TrainState, batches, accum: int):
        """One optimizer step from ``accum`` consecutive global batches
        pulled off ``batches`` (an iterator of device-resident batch
        dicts). Gradients AND batch-norm statistics are averaged over the
        microbatches. Returns (state, averaged metrics)."""
        if self._grad_step is None:
            self._build_accum_steps()
        with self.mesh:
            acc = None  # (grads_sum, metrics_sum, bs_sum)
            for _ in range(accum):
                grads, metrics, new_bs = self._grad_step(state, next(batches))
                new = (grads, metrics) if new_bs is None else (grads, metrics, new_bs)
                acc = new if acc is None else self._accum_add(acc, new)
            grads_sum, metrics_sum = acc[0], acc[1]
            bs_sum = acc[2] if len(acc) == 3 else None
            state = self._apply_step(state, grads_sum, bs_sum, accum)
        return state, {k: v / accum for k, v in metrics_sum.items()}

    def debug_step(self, state: TrainState, batch: Dict[str, jax.Array]):
        """Undonated train step for utils.debug determinism checks — the
        input state stays valid, so the same (state, batch) can be
        replayed and fingerprinted."""
        if self._train_step is None:
            self._build_steps()
        if self._debug_step is None:
            self._debug_step = jax.jit(
                self._raw_train_step, out_shardings=(self.state_shardings, None)
            )
        with self.mesh:
            return self._debug_step(state, batch)

    def multi_step(self, state: TrainState, batch: Dict[str, jax.Array], k: int):
        """Run ``k`` train steps on the same batch inside ONE dispatch via an
        on-device ``lax.scan``. Amortizes per-dispatch host/RPC latency —
        essential for honest step-time measurement on remote-attached chips
        and for small models where dispatch dominates. Returns
        (state, stacked metrics with leading dim k)."""
        if self._train_step is None:
            self._build_steps()
        fn = self._scan_steps.get(k)
        if fn is None:
            raw = self._raw_train_step

            def scan_fn(state, batch):
                def body(s, _):
                    s2, m = raw(s, batch)
                    return s2, m
                return jax.lax.scan(body, state, None, length=k)

            fn = jax.jit(scan_fn, donate_argnums=0,
                         out_shardings=(self.state_shardings, None))
            self._scan_steps[k] = fn
        with self.mesh:
            return fn(state, batch)

    def evaluate(self, state: TrainState, batches,
                 use_ema: bool = False) -> Dict[str, float]:
        """Metrics accumulate as device scalars — one host sync at the
        end, not one per batch (a per-batch ``float(v)`` readback
        serializes dispatch against the device queue). ``use_ema``
        evaluates the EMA weights (same jit trace — only the leaves
        swap)."""
        if use_ema:
            if state.ema_params is None:
                raise ValueError("use_ema=True but the trainer was built "
                                 "with ema_decay=0")
            state = state.replace(params=state.ema_params)
        if self._eval_step is None:
            self._build_steps()
        sums: Optional[Dict[str, jax.Array]] = None
        count = 0
        with self.mesh:
            for batch in batches:
                metrics = self._eval_step(state, batch)
                sums = (
                    metrics if sums is None
                    else jax.tree.map(jnp.add, sums, metrics)
                )
                count += 1
        if sums is None:
            return {}
        host = jax.device_get(sums)
        return {k: float(v) / count for k, v in host.items()}

    # ---- epoch loop ---------------------------------------------------------

    def fit(
        self,
        state: TrainState,
        batches,  # iterator of host-local numpy batch dicts
        epochs: int,
        steps_per_epoch: int,
        val_batches: Optional[Callable[[], Any]] = None,  # () -> iterable of batch dicts
        checkpoint_manager=None,
        log_every: int = 0,
        heartbeat=None,  # train.resilience.Heartbeat
        fault_injector=None,  # train.resilience.FaultInjector (chaos tests)
        prefetch: int = 2,  # device-resident batches staged ahead (0 = inline)
        grad_accum: int = 1,  # microbatches accumulated per optimizer step
        val_use_ema: bool = False,  # validate the EMA weights (the ones exported)
    ) -> Tuple[TrainState, Dict[str, list]]:
        """Run the training loop; returns final state and a Keras-style
        history dict (the reference's ``history.history`` analog,
        ``train_tf_ps.py:674-679``), extended with the north-star timing
        metrics (step_time_ms, examples_per_sec)."""
        from pyspark_tf_gke_tpu.data.pipeline import prefetch_to_device

        data_sharding = batch_sharding(self.mesh)
        history: Dict[str, list] = {}
        # Host-side mirror of state.step: one sync here, then pure
        # increments — no per-step device readback for liveness.
        global_step = int(jax.device_get(state.step))
        prefetched = prefetch_to_device(batches, data_sharding, size=prefetch)
        device_batches = _CountingIterator(prefetched)
        try:
            return self._fit_epochs(
                state, device_batches, epochs, steps_per_epoch, val_batches,
                checkpoint_manager, log_every, heartbeat, fault_injector,
                history, global_step, grad_accum, val_use_ema,
            )
        finally:
            # Stop the prefetch worker: it must not keep draining the
            # caller's iterator after fit returns or raises (restart
            # wrappers reuse that iterator).
            prefetched.close()

    def _fit_epochs(
        self, state, device_batches, epochs, steps_per_epoch, val_batches,
        checkpoint_manager, log_every, heartbeat, fault_injector,
        history, global_step, grad_accum, val_use_ema=False,
    ):
        from pyspark_tf_gke_tpu.data.pipeline import put_global_batch

        self._event_log.emit(
            "train_fit_start", task=self.task.name, epochs=epochs,
            steps_per_epoch=steps_per_epoch, start_step=global_step,
            grad_accum=grad_accum)
        for epoch in range(epochs):
            # Metrics accumulate as device scalars — no host sync inside the
            # step loop, so dispatch overlaps with next-batch preparation.
            sums: Dict[str, jax.Array] = {}
            t_first_step = 0.0
            epoch_start = time.perf_counter()
            examples = 0
            for step_i in range(steps_per_epoch):
                rows_before = device_batches.rows
                t0 = time.perf_counter()
                if grad_accum > 1:
                    state, metrics = self.accum_step(state, device_batches, grad_accum)
                else:
                    state, metrics = self.step(state, next(device_batches))
                if step_i == 0:
                    # first step includes compilation; keep it out of step-time stats
                    jax.block_until_ready(metrics)
                    t_first_step = time.perf_counter() - t0
                # global rows consumed this optimizer step
                step_rows = device_batches.rows - rows_before
                examples += step_rows
                global_step += 1
                # obs plane: counters record everything; the histogram
                # records steady steps only — each epoch's step 0 is
                # excluded (epoch 0's includes compile; later epochs'
                # absorb the drained dispatch queue at the
                # block_until_ready above), mirroring the history's
                # steady_steps accounting. Steady observations are the
                # host dispatch interval: with the step loop kept
                # async by design, this equals device step time once
                # the in-flight queue saturates, and under-reads it
                # before then — the history's synced epoch-level
                # step_time_ms stays the calibration reference.
                self._obs["train_steps_total"].inc()
                self._obs["train_examples_total"].inc(step_rows)
                if step_i != 0:
                    self._obs["train_step_time_ms"].observe(
                        (time.perf_counter() - t0) * 1000.0)
                if heartbeat is not None:
                    heartbeat.beat(global_step)
                if fault_injector is not None:
                    fault_injector.maybe_fail(global_step)
                for k, v in metrics.items():
                    sums[k] = sums[k] + v if k in sums else v
                if log_every and (step_i + 1) % log_every == 0:
                    logger.info(
                        "epoch %d step %d/%d loss=%.4f",
                        epoch + 1, step_i + 1, steps_per_epoch,
                        float(sums.get("loss", 0.0)) / (step_i + 1),
                    )
            sums_host = {k: float(jax.device_get(v)) for k, v in sums.items()}
            jax.block_until_ready(state.step)
            epoch_time = time.perf_counter() - epoch_start

            for k, v in sums_host.items():
                history.setdefault(k, []).append(v / steps_per_epoch)
            steady_steps = max(steps_per_epoch - 1, 1)
            steady_time = max(epoch_time - t_first_step, 1e-9)
            steady_examples = examples * steady_steps / steps_per_epoch
            step_ms = steady_time / steady_steps * 1000.0
            history.setdefault("step_time_ms", []).append(step_ms)
            history.setdefault("examples_per_sec", []).append(steady_examples / steady_time)

            msg = " - ".join(
                f"{k}: {history[k][-1]:.4f}" for k in sums
            )
            logger.info("Epoch %d/%d - %s - %.1f ms/step", epoch + 1, epochs, msg, step_ms)
            self._obs["train_epochs_total"].inc()
            if "loss" in history:
                self._obs["train_last_loss"].set(history["loss"][-1])
            self._event_log.emit(
                "train_epoch_end", epoch=epoch + 1, global_step=global_step,
                step_time_ms=round(step_ms, 3),
                loss=history.get("loss", [None])[-1])

            if val_batches is not None:
                val_sharding = batch_sharding(self.mesh)
                val_iter = (
                    put_global_batch(b, val_sharding) for b in val_batches()
                )
                val_metrics = self.evaluate(state, val_iter,
                                            use_ema=val_use_ema)
                for k, v in val_metrics.items():
                    history.setdefault(f"val_{k}", []).append(v)
                logger.info(
                    "Epoch %d validation - %s", epoch + 1,
                    " - ".join(f"{k}: {v:.4f}" for k, v in val_metrics.items()),
                )

            if checkpoint_manager is not None:
                checkpoint_manager.maybe_save(state, history)

        return state, history
