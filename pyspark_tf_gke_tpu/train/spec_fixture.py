"""Trained target/draft fixture for speculative decoding.

Random weights give near-zero acceptance (the lower bound) and a
self-draft gives exactly 1.0 (the upper bound); neither resembles a
deployed draft/target pair, so the spec bench and tests said almost
nothing about real speculative behavior (round-3 VERDICT, Weak #5).

This module trains a tiny byte-level target and a smaller draft on the
SAME low-entropy synthetic text for a few hundred Adam steps — enough
for both to lock onto the distribution, so the draft's greedy proposals
agree with the target's often but not always. The whole training loop
is one ``lax.scan`` under one jit per model (seconds on CPU, trivial on
a chip), deterministic by seed.

Text source: sentences drawn from a tiny first-order Markov chain over
a dozen words (seeded). The entropy is low enough that two different
model sizes both learn it quickly, and high enough (branching successors)
that a half-size draft keeps disagreeing with the target sometimes —
which is exactly the regime speculative decoding is for.

Reference counterpart: none (the reference has no generation at all);
the fixture pattern follows the standard practice of evaluating
speculative decoding with a distilled/smaller draft of the same data.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# word -> possible successors; deterministic-ish chain with branching so
# a smaller model stays imperfect on it
_CHAIN = {
    "the": ["tpu", "mesh", "ring", "chip"],
    "tpu": ["shards", "runs", "compiles"],
    "mesh": ["shards", "holds"],
    "ring": ["passes", "runs"],
    "chip": ["runs", "holds"],
    "shards": ["the"],
    "runs": ["the", "fast", "."],
    "holds": ["the"],
    "passes": ["the"],
    "compiles": ["the", "fast", "."],
    "fast": ["."],
    ".": ["the"],
}


def synthetic_text(n_chars: int, seed: int = 0,
                   skew: float = 0.75) -> str:
    """First successor drawn with p=``skew``, the rest uniform: the
    SKEW is load-bearing. With uniform branching the conditional argmax
    at a branch point is a near-tie, so two independently trained
    models pick branches by optimization noise and greedy acceptance
    collapses (measured: longer training DROPPED acceptance, and
    CPU-f32 vs TPU numerics landed on different sides of 0.5). A clear
    favorite gives both models the same learnable ranking;
    disagreements move to the genuinely hard spots (word boundaries
    under the draft's smaller context capacity), which is the regime
    speculative decoding deploys in. The default rose 0.6 -> 0.75 in
    round 5: 0.6 margins survived CPU f32 (0.84 acceptance) but not
    the TPU's pass-shape reduction noise (0.31 — the draft's s=1
    decode and the target's chunked verify reduce rows in different
    orders, flipping near-argmax ties; the self-draft ceiling itself
    measured 0.944). Bigger margins are the only fix that keeps greedy
    acceptance meaningful across backends."""
    rng = np.random.default_rng(seed)
    words, word = [], "the"
    total = 0
    while total < n_chars:
        words.append(word)
        total += len(word) + 1
        succ = _CHAIN[word]
        if len(succ) == 1:
            word = succ[0]
        else:
            rest = (1.0 - skew) / (len(succ) - 1)
            p = np.asarray([skew] + [rest] * (len(succ) - 1))
            word = succ[int(rng.choice(len(succ), p=p))]
    return " ".join(words)


def _pack_rows(seq_len: int, n_rows: int, seed: int = 0,
               skew: float = 0.75) -> np.ndarray:
    """[n_rows, seq_len] int32 byte tokens cut from one generated stream."""
    from pyspark_tf_gke_tpu.data.text import ByteTokenizer

    tok = ByteTokenizer()
    stream = np.asarray(
        tok.encode(synthetic_text(seq_len * (n_rows + 1), seed=seed,
                                  skew=skew)),
        dtype=np.int32)
    need = seq_len * n_rows
    assert stream.size >= need, "generator under-produced"
    return stream[:need].reshape(n_rows, seq_len)


def _train_lm(model, rows: np.ndarray, steps: int, lr: float,
              seed: int):
    """A few hundred Adam steps over the fixed row set, the whole loop
    inside one jitted ``lax.scan`` (no per-step dispatch overhead —
    matters through the remote-TPU tunnel)."""
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    from pyspark_tf_gke_tpu.utils.seeding import make_rng

    params = nn.meta.unbox(
        jax.jit(model.init)(make_rng(seed), jnp.asarray(rows[:1]))["params"])
    tx = optax.adam(lr)
    data = jnp.asarray(rows)
    n_rows = rows.shape[0]

    def one_step(carry, i):
        params, opt = carry
        ids = jax.lax.dynamic_index_in_dim(data, i % n_rows, axis=0,
                                           keepdims=True)

        def loss_fn(p):
            logits = model.apply({"params": p}, ids, train=True)
            lg = logits[:, :-1].astype(jnp.float32)
            per = optax.softmax_cross_entropy_with_integer_labels(
                lg, ids[:, 1:])
            return per.mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return (optax.apply_updates(params, updates), opt), loss

    @jax.jit
    def train(params):
        opt = tx.init(params)
        (params, _), losses = jax.lax.scan(
            one_step, (params, opt), jnp.arange(steps))
        return params, losses[-1]

    # HIGHEST matmul precision: on TPU the default f32 matmul uses
    # bf16 passes, which shifts these tiny models' near-argmax logits
    # enough to change greedy agreements — the fixture's acceptance
    # must mean the same thing on every backend (the first full
    # hardware capture measured 0.327 where CPU f32 gives ~0.6, purely
    # from this). Costs nothing at h64/h32 scale.
    with jax.default_matmul_precision("highest"):
        params, _ = train(params)
    return params


def make_spec_fixture(steps: int = 1500, seq_len: int = 64,
                      seed: int = 0, skew: float = 0.75) -> Tuple:
    """Returns ``(target, tparams, draft, dparams, prompt)``: a trained
    2-layer h64 byte target, a trained 1-layer h32 draft (same data),
    and an in-distribution prompt row. Deterministic by seed.

    The 1500-step default and the skewed chain are sized for BACKEND
    ROBUSTNESS, not convergence: with uniform branching, acceptance was
    noise (0.59 CPU / 0.33 TPU at 400 steps; MORE training made it
    WORSE on CPU — 0.45 at 1500 — because sharper models tie-break
    branch points differently). The 0.6-skewed chain made the ranking
    learnable (0.84 on CPU f32) but its margins still lost to TPU
    pass-shape reduction noise (0.31 measured, against a 0.944
    self-draft ceiling); skew 0.75 keeps the CPU middle (0.818 at 1500
    steps) with roughly doubled logit margins for the TPU argmax to
    hold (trail `bench.py spec` re-captures on the next window)."""
    import jax.numpy as jnp

    from pyspark_tf_gke_tpu.models import CausalLM, CausalLMConfig

    common = dict(vocab_size=259, intermediate_size=128, max_seq_len=256,
                  dtype=jnp.float32)
    tcfg = CausalLMConfig(hidden_size=64, num_layers=2, num_heads=4,
                          **common)
    dcfg = CausalLMConfig(hidden_size=32, num_layers=1, num_heads=2,
                          **{**common, "intermediate_size": 64})
    rows = _pack_rows(seq_len, n_rows=32, seed=seed, skew=skew)
    target, draft = CausalLM(tcfg), CausalLM(dcfg)
    tparams = _train_lm(target, rows, steps, lr=3e-3, seed=seed)
    dparams = _train_lm(draft, rows, steps, lr=3e-3, seed=seed + 1)
    prompt = jnp.asarray(_pack_rows(16, n_rows=1, seed=seed + 2,
                                    skew=skew))
    return target, tparams, draft, dparams, prompt
