"""Slot-based continuous batching: the serving plane's request engine.

The reference's serving story is one-at-a-time prediction over a saved
model (``/root/reference/workloads/raw-tf/test-model.py:13-56``). A real
serving plane cannot afford that: decode is HBM-bound, so throughput
comes from keeping every KV-cache slot busy — and requests arrive and
finish at different times, so a whole-batch ``generate`` (everyone
enters and exits together, the batch lives as long as its longest
member) leaves slots idle exactly when load is highest.

This engine is the TPU-idiomatic version of vLLM/TGI-style continuous
batching, built for XLA's compilation model instead of CUDA kernels:

- **Static shapes everywhere.** A fixed pool of ``num_slots`` KV-cache
  rows; prompts prefill through a small set of length buckets; decode is
  ONE compiled program per (model, chunk) regardless of which requests
  occupy which slots. No recompiles at serve time after warmup.
- **Per-row cache positions** (``models/causal_lm.py`` ``slot_decode``):
  each batch row writes K/V at its own fill level and masks attention
  against it, so row b can be 900 tokens into its answer while row b+1
  is on token 3 of a fresh request.
- **Admission at chunk boundaries.** The host loop runs a jitted
  ``lax.scan`` of ``chunk`` decode steps, then admits queued requests
  into freed slots (prefill writes the slot's cache rows directly).
  Through a remote-dispatch link the chunk amortizes per-dispatch
  latency; on a local TPU host it amortizes Python.
- **Right-padded bucketed prefill is exact**: causal attention means a
  real token's K/V and logits never see the padding AFTER it, and pad
  rows in the cache beyond a slot's fill level are masked by the
  per-row validity test (``k_pos <= fill``) until real decode tokens
  overwrite them one by one.

Greedy decoding (the deterministic serving path — parity-tested
token-for-token against ``models.causal_lm.generate``). Weight-only
int8 params and int8 KV cache both ride along: prefill dequantizes
inside its jit, the decode chunk uses the same in-loop barriered
dequant as ``_decode``, and the per-row cache write quantizes per row.

Single-process engine (one host driving one chip or a tp-sharded mesh
via module-level jits); the multi-host announce/replay serving wire
(``train/serving.py``) stays the cross-process surface.
"""

from __future__ import annotations

import contextlib
import functools
import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pyspark_tf_gke_tpu.chaos.inject import chaos_fire
from pyspark_tf_gke_tpu.models.causal_lm import CausalLM
from pyspark_tf_gke_tpu.obs.metrics import platform_families
from pyspark_tf_gke_tpu.obs.stepstats import StepStatsRing, flops_per_token
from pyspark_tf_gke_tpu.obs.trace import annotate_request_shape
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("train.continuous")

PAD_BUCKETS = (32, 64, 128, 256, 512, 1024)

# Smallest chunk the budget-aligned adaptive scheduler will dispatch:
# floors the jit-cache size (adaptive sizes are powers of two between
# this and the engine's ``chunk``) and bounds the overshoot on a
# sub-minimum remainder.
_MIN_ADAPTIVE_CHUNK = 8


def right_pad(tokens: np.ndarray, width: int,
              pad_id: int) -> np.ndarray:
    """[1, width] int32 row: tokens then pad (the prefill/extend input
    shape)."""
    row = np.full((1, width), pad_id, np.int32)
    row[0, :tokens.size] = tokens
    return row


def bucket_length(n: int, buckets: Sequence[int] = PAD_BUCKETS) -> int:
    """Smallest bucket >= n (compile-count control: one prefill program
    per bucket, not per prompt length)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest bucket {buckets[-1]}")


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray            # [S_true] int32
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    done: bool = False
    # multi-tenant fairness: every request belongs to a tenant (the
    # serving front defaults absent ids to "default"); the DWRR
    # admission scheduler arbitrates between tenants' subqueues and
    # the front's quota buckets charge/refund per tenant
    tenant: str = "default"
    # time.monotonic() at submit — /loadz queue_delay_ms (the HPA
    # latency signal) is the age of the OLDEST queued request
    enqueued_at: float = 0.0
    # streaming: called with each newly decoded token group, on the
    # engine's driver thread (keep it cheap — enqueue and return)
    on_tokens: Optional[callable] = None
    # sampling lane (temperature 0 = greedy; per-request PRNG seed)
    temperature: float = 0.0
    top_p: Optional[float] = None
    seed: int = 0
    # absolute time.monotonic() deadline (None = no deadline); past it
    # the request is expired at the next chunk boundary — queued ones
    # never admit, in-slot ones free their KV slot immediately
    deadline: Optional[float] = None
    expired: bool = False
    # time.monotonic() of the last token delivery — the per-request
    # time-between-tokens (serve_tbt_ms) clock; None until the first
    # tokens land (the first gap is TTFT, not TBT)
    last_emit: Optional[float] = None
    # speculative decoding tallies (spec engines only): draft tokens
    # proposed/accepted for THIS request while it still had budget —
    # the per-request accept-rate span event's source
    spec_proposed: int = 0
    spec_accepted: int = 0
    # request-attached trace span (obs/trace.py, or None): the engine
    # annotates the request's OWN span — queue wait, admission route,
    # prefill pieces, first token, token deliveries — so the timeline
    # lands on the trace the HTTP layer opened without the engine ever
    # knowing about transports. Every annotation is guarded on None:
    # bench/direct callers pay one attribute check per event site.
    span: Optional[object] = None


def _prefill_padded(model: CausalLM, params, padded_ids, true_len):
    """Prefill on a right-padded [1, S_bucket] prompt. Returns the full
    cache and the logits at the LAST REAL token (index true_len-1 —
    ``_prefill``'s logits[:, -1] would read a pad position). Causality
    makes the padding invisible to every real position. Exactly the
    batch-1 case of ``_prefill_padded_batch`` — delegated so the two
    cannot drift."""
    return _prefill_padded_batch(model, params, padded_ids,
                                 jnp.asarray(true_len)[None])


@functools.partial(jax.jit, static_argnames=("model",))
def _prefill_padded_batch(model: CausalLM, params, padded_ids, true_lens):
    """Batched right-padded prefill: ``[k, S_bucket]`` prompts with
    per-row true lengths, ONE weight-streaming forward. The batch-1
    admission loop pays the full HBM weight read per request — on the
    round-5 hardware trail that made slot refills the engine's dominant
    overhead vs whole-batch serving (32 batch-1 prefills vs 4 batch-8
    ones; prefill is bandwidth-bound, so batch-1 costs nearly as much
    as batch-8). Returns the k-row cache tree and the logits at each
    row's last real token."""
    from pyspark_tf_gke_tpu.ops.quant import dequantize_tree

    logits, mutated = model.apply(
        {"params": dequantize_tree(params)}, padded_ids, prefill=True,
        mutable=["cache"])
    last = jnp.take_along_axis(
        logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
    return mutated["cache"], last


@functools.partial(jax.jit, static_argnames=("model",))
def _extend_prefix(model: CausalLM, params, cache1, padded_rem, fill,
                   rem_len):
    """Extend a batch-1 prefix cache (fill level ``fill``) with the
    right-padded remainder tokens in ONE multi-token slot-decode
    forward: K/V for all remainder positions are written at
    fill..fill+s-1 and the causal offset mask keeps every real token
    blind to the padding after it (same argument as the padded
    prefill). Returns (extended cache, logits at the last REAL
    remainder token)."""
    from pyspark_tf_gke_tpu.ops.quant import dequantize_tree

    s_b = padded_rem.shape[1]
    positions = (fill + jnp.arange(s_b))[None, :]
    logits, mutated = model.apply(
        {"params": dequantize_tree(params), "cache": cache1}, padded_rem,
        decode=True, slot_decode=True, positions=positions,
        mutable=["cache"])
    last = jnp.take_along_axis(
        logits, (rem_len - 1)[None, None, None], axis=1)[:, 0]
    return mutated["cache"], last


class PrefixCache:
    """LRU of prefilled prompt PREFIXES (the shared-system-prompt
    serving pattern): each entry holds a batch-1 cache tree + the
    last-token logits at its fill level. ``lookup`` returns the longest
    entry that prefixes the prompt; admission inserts it into the slot
    and only the remainder pays prefill compute. Each entry costs one
    slot's worth of KV memory — size ``capacity`` accordingly."""

    def __init__(self, capacity: int = 4):
        if capacity < 1:
            raise ValueError("prefix cache capacity must be >= 1")
        self.capacity = capacity
        self._entries = {}  # key tuple -> (cache_tree, last_logits)
        self._order: List[tuple] = []  # LRU, most recent LAST
        self.hits = self.misses = 0

    def put(self, key_tokens, cache1, logits1) -> None:
        key = tuple(int(t) for t in key_tokens)
        if key in self._entries:
            self._order.remove(key)
        elif len(self._entries) >= self.capacity:
            evict = self._order.pop(0)
            del self._entries[evict]
        self._entries[key] = (cache1, logits1)
        self._order.append(key)

    def lookup(self, prompt: np.ndarray, peek: bool = False):
        """Best cached entry by LONGEST COMMON TOKEN PREFIX with the
        prompt — not exact key-prefix match, because BPE tokenizers are
        not prefix-stable: encode(system + user) can merge a token
        across the boundary, so the warmed sequence and the prompt
        diverge one token early. Matching the common prefix reuses
        every row up to the divergence and recomputes only the rest.
        Returns (usable_fill, cache_tree, last_logits_or_None) or None;
        ``last_logits`` is only returned when the WHOLE entry matched
        and equals the whole prompt's prefix fill (else the extension
        recomputes the logits anyway)."""
        toks = np.asarray(prompt, np.int64)
        best, best_common = None, 0
        for key in self._entries:
            k = np.asarray(key, np.int64)
            n = min(k.size, toks.size)
            neq = np.nonzero(k[:n] != toks[:n])[0]
            common = int(neq[0]) if neq.size else n
            if common > best_common:
                best, best_common = key, common
        # A prompt that is a STRICT prefix of an entry (common == prompt
        # length < entry length) would need logits at a fill level the
        # entry doesn't store — decline; everything else either matched
        # exactly (stored logits apply) or has a remainder whose
        # extension recomputes them.
        if best is None or best_common == 0 or (
                best_common == toks.size and best_common != len(best)):
            if not peek:
                self.misses += 1
            return None
        if not peek:
            self.hits += 1
            self._order.remove(best)
            self._order.append(best)  # LRU touch
        cache1, logits1 = self._entries[best]
        exact = best_common == len(best) == toks.size
        return best_common, cache1, (logits1 if exact else None)

    @property
    def stats(self) -> dict:
        return {"entries": len(self._entries), "capacity": self.capacity,
                "hits": self.hits, "misses": self.misses}


class _RadixNode:
    """One KV page in the radix prefix cache: ``tokens`` is the page's
    token content (``page_size`` long for interior/full pages, shorter
    for a TAIL page holding a partially-filled final page — always a
    leaf). Children are keyed by their token tuple, but LOOKUP scans
    children for the longest common prefix rather than dict-probing:
    two siblings may share an in-page prefix after divergent inserts
    ("efgh" and "efxy"), and a tail node matches any prompt that
    extends its tokens."""

    __slots__ = ("tokens", "page", "children", "parent", "last_used")

    def __init__(self, tokens: tuple, page: Optional[int], parent):
        self.tokens = tokens
        self.page = page
        self.parent = parent
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.last_used = 0


class RadixPrefixCache:
    """SGLang-style trie index over the PAGED KV pool (the engine owns
    the pages; this class owns only the token->page index): completed
    prompts' pages stay resident, a new prompt matches its longest
    cached prefix at page granularity and SHARES those pages
    copy-on-write, so prefill compute and pool traffic are ∝ the
    unique suffix only.

    Division of labor with the engine: the trie never touches device
    state or refcounts. ``match``/``insert``/``evict`` return page-id
    lists and the ENGINE moves the refcounts (+1 for every page the
    trie adopts, -1 for every page it releases) — one owner for the
    page lifecycle, so the refcount invariants are checkable in one
    place. Eviction is LRU over leaf nodes whose page has no slot
    reference (``busy`` predicate), leaf-first so a cached path is
    always contiguous from the root."""

    def __init__(self, page_size: int, capacity_pages: int):
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        if capacity_pages < 1:
            raise ValueError("capacity_pages must be >= 1")
        self.page_size = int(page_size)
        self.capacity = int(capacity_pages)
        self._root = _RadixNode((), None, None)
        self._tick = 0
        self.resident_pages = 0
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.evictions = 0
        # last-N admission outcomes: the hit-rate signal the router
        # scores spill allowance on must track CURRENT absorption, not
        # the lifetime ratio — a cache that went cold (eviction, mix
        # shift) would otherwise keep advertising its warm past
        self._recent: Deque[int] = deque(maxlen=64)

    @staticmethod
    def _common(a, b) -> int:
        n = min(len(a), len(b))
        i = 0
        while i < n and a[i] == b[i]:
            i += 1
        return i

    def _touch(self, node: _RadixNode) -> None:
        # the whole matched path was used: eviction is leaf-only, but
        # a deep leaf must keep its ancestors young for when IT is
        # evicted and they become leaves
        self._tick += 1
        while node is not None and node.page is not None:
            node.last_used = self._tick
            node = node.parent

    def match(self, prompt, limit: Optional[int] = None,
              peek: bool = False, count: bool = True):
        """Longest cached prefix of ``prompt``. Returns
        ``(matched_tokens, full_page_ids, cow)`` where ``cow`` is
        ``(src_page, rows)`` when the match ends INSIDE a page — the
        admission must clone those rows into a fresh page before its
        suffix can append there (copy-on-write; the full pages are
        shared read-only, the slot never writes below the match
        boundary). ``limit`` caps the match — default
        ``len(prompt) - 1``, because at least one suffix token must be
        computed to produce the carried decode logits (the trie stores
        pages, not logits). ``peek`` skips stats and LRU touching;
        ``count=False`` touches the LRU but leaves the hit/miss stats
        to an explicit ``note()`` — for callers whose effective match
        may still shrink (COW degrade) or that aren't admissions at
        all (warm no-ops): the hit rate is a ROUTING signal, so only
        real admission outcomes may feed it."""
        toks = tuple(int(t) for t in prompt)
        limit = len(toks) - 1 if limit is None else min(limit, len(toks))
        node = self._root
        t = 0
        pages: List[int] = []
        cow = None
        last = None
        while t < limit:
            rem = toks[t:]
            best, best_c = None, 0
            for child in node.children.values():
                c = self._common(child.tokens, rem)
                if c > best_c:
                    best, best_c = child, c
            if best is None:
                break
            best_c = min(best_c, limit - t)
            if best_c <= 0:
                break
            last = best
            if best_c == len(best.tokens) == self.page_size:
                pages.append(best.page)
                t += self.page_size
                node = best
                continue
            # partial in-page match: a tail node, a mid-page
            # divergence, or the limit cap — the walk ends here
            cow = (best.page, best_c)
            t += best_c
            break
        if not peek:
            if last is not None:
                # ONE root-ward walk from the deepest matched node
                # marks the whole path (O(depth), not O(depth^2));
                # leaf-first eviction makes intra-path order moot
                self._touch(last)
            if count:
                self.note(t)
        return t, pages, cow

    def note(self, matched: int) -> None:
        """Record one ADMISSION outcome: the cumulative hit counters
        plus the recent-outcome window behind ``recent_hit_rate``."""
        if matched > 0:
            self.hits += 1
            self.hit_tokens += int(matched)
            self._recent.append(1)
        else:
            self.misses += 1
            self._recent.append(0)

    @property
    def recent_hit_rate(self) -> float:
        """Hit rate over the last up-to-64 admissions — what ``/loadz``
        exports for the router's spill allowance. Windowed, not
        lifetime: a cache that went cold (eviction, traffic-mix shift)
        stops advertising its warm past within one window."""
        if not self._recent:
            return 0.0
        return sum(self._recent) / len(self._recent)

    def insert(self, tokens, pages):
        """Index ``tokens`` (chunked per page) over their physical
        ``pages`` (block-table row order). Chunks an existing node
        already covers are NOT re-adopted (the duplicate page simply
        loses its slot ref when the caller releases it); a partial
        tail node that is a strict prefix of a longer chunk is
        UPGRADED in place to the new, fuller page — that is how a
        cached conversation prefix grows turn by turn. Returns
        ``(adopted, released)`` page-id lists for the engine's
        refcount moves."""
        toks = tuple(int(t) for t in tokens)
        ps = self.page_size
        adopted: List[int] = []
        released: List[int] = []
        node = self._root
        self._tick += 1
        for i in range(0, len(toks), ps):
            chunk = toks[i:i + ps]
            page = int(pages[i // ps])
            nxt = None
            for child in node.children.values():
                c = self._common(child.tokens, chunk)
                if c == len(chunk) and len(child.tokens) >= len(chunk):
                    nxt = child  # already covered (possibly by a
                    break        # longer tail) — keep the cached page
                if c == len(child.tokens) and c < len(chunk):
                    # the cached tail is a strict prefix of our chunk:
                    # upgrade the node to the fuller page (identical
                    # token prefix -> identical KV rows; slots still
                    # reading the old page keep it alive by refcount)
                    del node.children[child.tokens]
                    released.append(child.page)
                    child.tokens = chunk
                    child.page = page
                    node.children[chunk] = child
                    adopted.append(page)
                    nxt = child
                    break
            if nxt is None:
                nxt = _RadixNode(chunk, page, node)
                node.children[chunk] = nxt
                adopted.append(page)
                self.resident_pages += 1
            nxt.last_used = self._tick
            if len(nxt.tokens) < ps or len(chunk) < ps:
                break  # a tail page ends the path
            node = nxt
        return adopted, released

    def evict(self, n_pages: int, busy) -> List[int]:
        """Drop up to ``n_pages`` least-recently-used LEAF pages whose
        page ``busy(page)`` reports free of slot references; returns
        the released page ids (the caller unrefs them back to the
        pool). Interior nodes become eligible as their children go —
        O(nodes) per eviction, fine at page-pool scale."""
        released: List[int] = []
        while len(released) < n_pages:
            victim = None
            stack = [self._root]
            while stack:
                node = stack.pop()
                for child in node.children.values():
                    if child.children:
                        stack.append(child)
                    elif not busy(child.page) and (
                            victim is None
                            or child.last_used < victim.last_used):
                        victim = child
            if victim is None:
                break  # everything left is pinned by live slots
            del victim.parent.children[victim.tokens]
            released.append(victim.page)
            self.resident_pages -= 1
            self.evictions += 1
        return released

    def indexed_pages(self) -> List[int]:
        """Every page the trie currently references (invariant checks:
        each must hold exactly one trie refcount)."""
        out: List[int] = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                out.append(child.page)
                stack.append(child)
        return out

    @property
    def stats(self) -> dict:
        return {"kind": "radix", "resident_pages": self.resident_pages,
                "capacity_pages": self.capacity, "hits": self.hits,
                "misses": self.misses, "hit_tokens": self.hit_tokens,
                "evictions": self.evictions,
                "recent_hit_rate": round(self.recent_hit_rate, 4)}


def _request_cost(req: "_Request") -> int:
    """A request's token footprint for fair-share accounting: prompt +
    full generation budget — the same upper bound bounded admission and
    the quota buckets charge (refunds reconcile unused budget later;
    the scheduler must arbitrate on the worst case it admits)."""
    return int(req.prompt.size) + int(req.max_new_tokens)


class DwrrScheduler:
    """Deficit-weighted round robin over per-tenant subqueues.

    Each tenant's subqueue is its arrival-ordered subsequence of the
    engine's admission queue (FIFO or LPT within a tenant — whatever
    the engine's ``schedule`` produced). Every rotation visit banks
    ``quantum * weight`` tokens of deficit; a tenant may admit its
    head-of-line request when its deficit covers the request's token
    cost (prompt + budget), paying the cost down on admission. Over a
    saturated queue the admitted-token shares converge to the weight
    ratio regardless of request sizes — the classic DWRR guarantee —
    while an idle tenant's unused deficit is dropped the moment its
    subqueue empties (no banking credit while absent, so a returning
    tenant cannot burst past its share).

    Pure host-side bookkeeping (no device state): the engine consults
    :meth:`pick` only once it has actually seen two distinct tenants —
    a single-tenant engine never enters this class and keeps the exact
    pre-fairness FIFO/LPT admission order (the FIFO-equivalent fast
    path the cb bench pins)."""

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 quantum: int = 256):
        if quantum < 1:
            raise ValueError(f"quantum must be >= 1, got {quantum}")
        self.weights: Dict[str, float] = {}
        for name, w in (weights or {}).items():
            w = float(w)
            if w <= 0:
                raise ValueError(
                    f"tenant {name!r} weight must be > 0, got {w}")
            self.weights[name] = w
        self.quantum = int(quantum)
        self._deficit: Dict[str, float] = {}
        self._rr: Deque[str] = deque()  # rotation over queued tenants
        # cumulative admitted token cost per tenant (stats + the
        # share-convergence tests' observable)
        self.admitted_tokens: Dict[str, int] = {}

    def weight(self, tenant: str) -> float:
        """Configured weight; unknown tenants fall back to the ``*``
        wildcard entry, then 1.0 — an unconfigured tenant competes at
        baseline weight instead of being refused."""
        w = self.weights.get(tenant)
        if w is None:
            w = self.weights.get("*", 1.0)
        return float(w)

    def pick(self, queue: List["_Request"]) -> int:
        """Index into ``queue`` of the request to admit next. The
        rotation/deficit state persists across calls; tenants that
        left the queue are dropped (deficit reset — no banking)."""
        heads: Dict[str, int] = {}
        for i, req in enumerate(queue):
            if req.tenant not in heads:
                heads[req.tenant] = i
        if len(heads) <= 1:
            return 0  # one tenant queued: its own order stands
        present = set(heads)
        for t in list(self._deficit):
            if t not in present:
                del self._deficit[t]
        if any(t not in present for t in self._rr):
            self._rr = deque(t for t in self._rr if t in present)
        for t in heads:  # first-appearance order joins at the back
            if t not in self._rr:
                self._rr.append(t)
        # rotate, banking quanta, until a head-of-line is affordable;
        # bounded: each full rotation banks quantum*weight for every
        # tenant and costs are bounded by max_seq_len, so the guard is
        # never the exit in practice — it exists so a pathological
        # weight/quantum config degrades to round-robin, not a wedge
        for _ in range(10000):
            t = self._rr[0]
            cost = _request_cost(queue[heads[t]])
            if self._deficit.get(t, 0.0) >= cost:
                return heads[t]
            self._deficit[t] = (self._deficit.get(t, 0.0)
                                + self.quantum * self.weight(t))
            self._rr.rotate(-1)
        return heads[self._rr[0]]

    def charge(self, req: "_Request") -> None:
        """Pay one admitted request's cost down from its tenant's
        deficit and tally it (the share the convergence tests
        measure)."""
        t = req.tenant
        cost = _request_cost(req)
        self._deficit[t] = self._deficit.get(t, 0.0) - cost
        self.admitted_tokens[t] = self.admitted_tokens.get(t, 0) + cost


def _seed_key_data(seed):
    """[2] uint32 key data for the slot lane, with the impl PINNED to
    threefry2x32: _decode_chunk wraps with that impl explicitly, and the
    default-impl PRNGKey would hand back (4,)-shaped rbg data on
    configs that set jax_default_prng_impl=rbg (common on TPU).

    Seeds in [0, 2**32) — every seed the serving stack generates —
    take a pure-numpy fast path: threefry key data for such a seed is
    exactly ``[0, seed]`` under x64 on AND off (verified bit-identical
    against ``jax.random.key``), and building it on the host instead
    of through three eager device ops keeps admissions off the
    dispatch queue (measured ~0.14 ms/row on the CPU bench box —
    admission host cost is what the double-buffered loop must hide).
    Out-of-range seeds keep the jax path, whose truncation semantics
    depend on the x64 flag and are not worth reimplementing."""
    s = int(seed)
    if 0 <= s < 2**32:
        return np.array([0, s], np.uint32)
    return jax.random.key_data(
        jax.random.key(s, impl="threefry2x32")).astype(jnp.uint32)


class SlotState(NamedTuple):
    """The slot pool's device arrays (a pytree — flows through jits).
    Sampling lanes ride per slot: ``temps`` 0 = greedy for that row,
    ``topps`` 1 = no nucleus filter, ``keys`` a per-slot PRNG key each
    sampling row folds forward every step."""

    cache: Any
    positions: jnp.ndarray     # [B] int32 fill levels
    last_logits: jnp.ndarray   # [B, V] carried logits
    live: jnp.ndarray          # [B] bool
    temps: jnp.ndarray         # [B] f32
    topps: jnp.ndarray         # [B] f32
    keys: jnp.ndarray          # [B, 2] uint32


@jax.jit
def _clear_live(state: SlotState, slot):
    return state._replace(live=state.live.at[slot].set(False))


# -- paged KV cache (models/causal_lm.py CausalLMConfig.kv_num_pages) --------
#
# Slot mode stores K/V in one global page pool per layer plus a per-slot
# block table; the ENGINE owns page allocation (host-side free list,
# admit/free boundaries only — no mid-decode allocation, so no shape
# recompiles). Prefill still runs on the dense batch-1 layout (it is
# compute-bound and transient); the insert ops below scatter its rows
# into the slot's pages. A slot's block-table row is reset to the
# OUT-OF-RANGE sentinel on free, so rows of freed/dead slots can never
# write into pages reallocated to another request.


def _map_paged_layers(pool_tree, fn, dense_tree=None):
    """Rebuild a paged cache tree: ``fn`` is applied to every subtree
    holding the paged leaves (``k_pages``/``block_table``/...), paired
    with the same-path subtree of ``dense_tree`` when given (the dense
    prefill cache has ``k``/``v``/``index`` at identical paths — both
    come from the same attention modules)."""
    def walk(pool, dense):
        if hasattr(pool, "keys"):
            if "k_pages" in pool:
                return fn(pool) if dense is None else fn(pool, dense)
            return {key: walk(pool[key],
                              None if dense is None else dense[key])
                    for key in pool}
        return pool
    return walk(pool_tree, dense_tree)


@functools.partial(jax.jit, static_argnames=("model", "num_slots"))
def _paged_zeros_state(model: CausalLM, params, *,
                       num_slots: int) -> SlotState:
    """Fresh paged slot-pool state. The paged cache tree's shapes come
    from the model config, not from a prefill template, so it is built
    by one throwaway slot-decode forward whose cache writes all drop
    (block tables initialize to the sentinel)."""
    from pyspark_tf_gke_tpu.ops.quant import dequantize_tree

    b = num_slots
    tok = jnp.zeros((b, 1), jnp.int32)
    pos = jnp.zeros((b, 1), jnp.int32)
    _, mutated = model.apply(
        {"params": dequantize_tree(params)}, tok, decode=True,
        slot_decode=True, positions=pos, mutable=["cache"])
    return SlotState(
        cache=mutated["cache"],
        positions=jnp.zeros((b,), jnp.int32),
        last_logits=jnp.zeros((b, model.cfg.vocab_size), jnp.float32),
        live=jnp.zeros((b,), bool),
        temps=jnp.zeros((b,), jnp.float32),
        topps=jnp.ones((b,), jnp.float32),
        keys=jnp.zeros((b, 2), jnp.uint32))


@functools.partial(jax.jit, static_argnames=("n_rows",))
def _insert_slot_paged(state: SlotState, cache1, logits1, slot, fill,
                       pages, temp, topp, key, *, n_rows: int) -> SlotState:
    """Paged ``_insert_slot``: scatter the dense batch-1 prefill's first
    ``n_rows`` cache rows (the padded bucket — ``n_rows`` static, so
    one program per bucket) into the slot's allocated pages and point
    its block-table row at them. ``pages`` is the sentinel-padded
    ``[max_pages_per_slot]`` allocation; only its first
    ``n_rows / page_size`` entries receive prefill rows."""
    def layer(pool, dense):
        ps = pool["k_pages"].shape[1]
        nc = n_rows // ps
        idx = pages[:nc]

        def scat(pool_leaf, dense_leaf):
            rows = dense_leaf[0, :n_rows]
            chunks = rows.reshape((nc, ps) + rows.shape[1:])
            return pool_leaf.at[idx].set(
                chunks.astype(pool_leaf.dtype), mode="drop")

        out = dict(pool)
        out["k_pages"] = scat(pool["k_pages"], dense["k"])
        out["v_pages"] = scat(pool["v_pages"], dense["v"])
        if "k_scale_pages" in pool:
            out["k_scale_pages"] = scat(pool["k_scale_pages"],
                                        dense["k_scale"])
            out["v_scale_pages"] = scat(pool["v_scale_pages"],
                                        dense["v_scale"])
        out["block_table"] = pool["block_table"].at[slot].set(pages)
        out["index"] = jnp.maximum(pool["index"], dense["index"])
        return out

    cache = _map_paged_layers(state.cache, layer, cache1)
    return SlotState(
        cache=cache,
        positions=state.positions.at[slot].set(fill),
        last_logits=state.last_logits.at[slot].set(logits1[0]),
        live=state.live.at[slot].set(True),
        temps=state.temps.at[slot].set(temp),
        topps=state.topps.at[slot].set(topp),
        keys=state.keys.at[slot].set(key))


@functools.partial(jax.jit, static_argnames=("n_rows",))
def _insert_slots_batch_paged(state: SlotState, caches, logits, slots,
                              fills, pages_b, temps, topps, keys, *,
                              n_rows: int) -> SlotState:
    """Paged ``_insert_slots_batch``: one scatter lands every admitted
    row's prefill pages AND block-table rows. Shape-padding rows carry
    the out-of-bounds slot sentinel and all-sentinel page rows, so
    both scatters drop them."""
    def layer(pool, dense):
        ps = pool["k_pages"].shape[1]
        nc = n_rows // ps
        idx = pages_b[:, :nc].reshape(-1)

        def scat(pool_leaf, dense_leaf):
            rows = dense_leaf[:, :n_rows]
            chunks = rows.reshape(
                (rows.shape[0] * nc, ps) + rows.shape[2:])
            return pool_leaf.at[idx].set(
                chunks.astype(pool_leaf.dtype), mode="drop")

        out = dict(pool)
        out["k_pages"] = scat(pool["k_pages"], dense["k"])
        out["v_pages"] = scat(pool["v_pages"], dense["v"])
        if "k_scale_pages" in pool:
            out["k_scale_pages"] = scat(pool["k_scale_pages"],
                                        dense["k_scale"])
            out["v_scale_pages"] = scat(pool["v_scale_pages"],
                                        dense["v_scale"])
        out["block_table"] = pool["block_table"].at[slots].set(
            pages_b, mode="drop")
        out["index"] = jnp.maximum(pool["index"], dense["index"])
        return out

    cache = _map_paged_layers(state.cache, layer, caches)
    return SlotState(
        cache=cache,
        positions=state.positions.at[slots].set(fills, mode="drop"),
        last_logits=state.last_logits.at[slots].set(logits, mode="drop"),
        live=state.live.at[slots].set(True, mode="drop"),
        temps=state.temps.at[slots].set(temps, mode="drop"),
        topps=state.topps.at[slots].set(topps, mode="drop"),
        keys=state.keys.at[slots].set(keys, mode="drop"))


@functools.partial(jax.jit, static_argnames=("model",))
def _paged_prefill_chunk(model: CausalLM, params, state: SlotState,
                         padded, fill, true_len, row):
    """One chunked-prefill piece written STRAIGHT into the page pool
    (no dense staging cache, no scatter): a batch-1 multi-token
    slot-decode forward whose cache view aliases the shared pool
    leaves but substitutes ``row`` (the admission's sentinel-padded
    page allocation) for the block table — the SLOT STATE's own table
    row stays at the sentinel until activation, so interleaved decode
    chunks' dead-row writes for the reserved slot drop instead of
    corrupting the half-written prompt. Returns ``(state with updated
    pool leaves, logits at the piece's last REAL token)``. Width is
    static: one compiled program per piece width."""
    from pyspark_tf_gke_tpu.ops.quant import dequantize_tree

    def view(pool):
        out = dict(pool)
        out["block_table"] = row[None]
        return out

    cache1 = _map_paged_layers(state.cache, view)
    w = padded.shape[1]
    positions = (fill + jnp.arange(w))[None, :]
    logits, mutated = model.apply(
        {"params": dequantize_tree(params), "cache": cache1}, padded,
        decode=True, slot_decode=True, positions=positions,
        mutable=["cache"])

    def merge(pool, new):
        out = dict(pool)
        for key in ("k_pages", "v_pages", "k_scale_pages",
                    "v_scale_pages"):
            if key in pool:
                out[key] = new[key]
        out["index"] = jnp.maximum(pool["index"], new["index"])
        return out

    cache = _map_paged_layers(state.cache, merge, mutated["cache"])
    last = jnp.take_along_axis(
        logits, (true_len - 1)[None, None, None], axis=1)[:, 0]
    return state._replace(cache=cache), last


@jax.jit
def _activate_slot_paged(state: SlotState, slot, row, fill, logits1,
                         temp, topp, key) -> SlotState:
    """Chunked-prefill admission complete: point the slot's block-table
    row at the admission's pages (every piece already lives in them)
    and flip the slot live with its fill level, carried logits and
    sampling lane — the paged analog of ``_insert_slot`` with no cache
    rows to move."""
    def layer(pool):
        out = dict(pool)
        out["block_table"] = pool["block_table"].at[slot].set(row)
        return out

    return SlotState(
        cache=_map_paged_layers(state.cache, layer),
        positions=state.positions.at[slot].set(fill),
        last_logits=state.last_logits.at[slot].set(logits1[0]),
        live=state.live.at[slot].set(True),
        temps=state.temps.at[slot].set(temp),
        topps=state.topps.at[slot].set(topp),
        keys=state.keys.at[slot].set(key))


@jax.jit
def _copy_page(state: SlotState, src, dst):
    """Copy-on-write clone of one KV page (every layer's K/V leaves,
    int8 scale pages included): the radix prefix cache shares FULL
    pages read-only, but a match that ends inside a partially-filled
    tail page must clone it before the new slot can append its suffix
    rows there — the source page may be read concurrently by the trie
    and other slots. Whole-page copy (static shape, one compiled
    program for any src/dst pair); rows past the matched fill are
    garbage the suffix prefill overwrites or the fill mask hides."""
    def layer(pool):
        out = dict(pool)
        for key in ("k_pages", "v_pages", "k_scale_pages",
                    "v_scale_pages"):
            if key in pool:
                out[key] = pool[key].at[dst].set(pool[key][src],
                                                 mode="drop")
        return out

    return state._replace(cache=_map_paged_layers(state.cache, layer))


# the paged leaves that travel in a KV-page transfer, in WIRE ORDER —
# export, import, and the OP_KV_XFER replay all iterate this tuple, so
# the per-layer payload dicts line up across processes and replicas
_KV_XFER_KEYS = ("k_pages", "v_pages", "k_scale_pages", "v_scale_pages")


@jax.jit
def _gather_pages(state: SlotState, idx):
    """Gather the rows of pages ``idx`` from every layer's pool leaves
    (K/V pages, int8 scale pages included) — the prefill side of a
    disaggregated KV handoff. Returns one dict per paged layer in tree
    walk order. Out-of-range (sentinel-padded) indices clamp; the
    caller slices the real rows off the host copy."""
    out = []

    def layer(pool):
        out.append({key: pool[key][idx] for key in _KV_XFER_KEYS
                    if key in pool})
        return pool

    _map_paged_layers(state.cache, layer)
    return out


@jax.jit
def _install_pages(state: SlotState, idx, blobs):
    """Scatter transferred KV page rows into the pool at physical
    indices ``idx`` (one dict per paged layer, float32 on the wire —
    cast back to each leaf's pool dtype; sentinel-padded indices
    drop) — the decode side of a disaggregated KV handoff."""
    it = iter(blobs)

    def layer(pool):
        rec = next(it)
        out = dict(pool)
        for key in _KV_XFER_KEYS:
            if key in pool:
                out[key] = pool[key].at[idx].set(
                    rec[key].astype(pool[key].dtype), mode="drop")
        return out

    return state._replace(cache=_map_paged_layers(state.cache, layer))


@jax.jit
def _clear_live_paged(state: SlotState, slot):
    """Paged free: drop the live flag AND reset the slot's block-table
    row to the sentinel, so in-flight dead-row replays (decode-ahead)
    scatter nowhere instead of into pages the engine is about to hand
    to another request."""
    def layer(pool):
        out = dict(pool)
        n = pool["k_pages"].shape[0]
        mp = pool["block_table"].shape[1]
        out["block_table"] = pool["block_table"].at[slot].set(
            jnp.full((mp,), n, jnp.int32))
        return out

    return state._replace(
        cache=_map_paged_layers(state.cache, layer),
        live=state.live.at[slot].set(False))


@functools.partial(jax.jit, static_argnames=("num_slots", "vocab"))
def _zeros_state(cache1, *, num_slots: int, vocab: int) -> SlotState:
    """Fresh slot-pool state shaped after one prefill's cache tree."""
    b = num_slots
    cache = jax.tree.map(
        lambda row: (jnp.zeros_like(row) if row.ndim == 0
                     else jnp.zeros((b,) + row.shape[1:], row.dtype)),
        cache1)
    return SlotState(
        cache=cache,
        positions=jnp.zeros((b,), jnp.int32),
        last_logits=jnp.zeros((b, vocab), jnp.float32),
        live=jnp.zeros((b,), bool),
        temps=jnp.zeros((b,), jnp.float32),
        topps=jnp.ones((b,), jnp.float32),
        keys=jnp.zeros((b, 2), jnp.uint32))


@jax.jit
def _insert_slots_batch(state: SlotState, caches, logits, slots, fills,
                        temps, topps, keys) -> SlotState:
    """Batched ``_insert_slot``: scatter a batched prefill's rows into
    the slot pool in ONE compiled program. The first cut looped batch-1
    inserts over sliced rows — hundreds of tiny slice/insert dispatches
    whose submission overhead over a remote tunnel UNDID the batched
    prefill's win (round-5 trail: 1774 -> 1197 tok/s). Every operand is
    padded to the power-of-two batch ``k_pad`` by the caller and
    ``slots`` is a traced [k_pad] index vector whose pad entries hold
    the OUT-OF-BOUNDS sentinel ``num_slots`` — jnp scatter drops
    out-of-bounds updates, so pad rows never land and the program count
    stays one per k_pad shape (a static real-k argument would have
    compiled one program per group size 2..num_slots, paid inside the
    first measured serving run)."""
    cache = jax.tree.map(
        lambda big, rows: (jnp.maximum(big, rows) if rows.ndim == 0
                           else big.at[slots].set(rows, mode="drop")),
        state.cache, caches)
    return SlotState(
        cache=cache,
        positions=state.positions.at[slots].set(fills, mode="drop"),
        last_logits=state.last_logits.at[slots].set(logits, mode="drop"),
        live=state.live.at[slots].set(True, mode="drop"),
        temps=state.temps.at[slots].set(temps, mode="drop"),
        topps=state.topps.at[slots].set(topps, mode="drop"),
        keys=state.keys.at[slots].set(keys, mode="drop"))


@jax.jit
def _insert_slot(state: SlotState, cache1, logits1, slot, fill,
                 temp, topp, key) -> SlotState:
    """Drop a prefilled request into slot ``slot`` (traced scalar — one
    compiled program serves every slot): cache rows, fill level, carried
    logits, live flag, sampling lane."""
    # Scalar leaves are the per-layer `index` fill counters — unused by
    # slot mode (per-row positions are the authority) but kept
    # conservative (max) so any non-slot reader of the cache var sees a
    # safe fill level.
    cache = jax.tree.map(
        lambda big, row: (jnp.maximum(big, row) if row.ndim == 0
                          else big.at[slot].set(row[0])),
        state.cache, cache1)
    return SlotState(
        cache=cache,
        positions=state.positions.at[slot].set(fill),
        last_logits=state.last_logits.at[slot].set(logits1[0]),
        live=state.live.at[slot].set(True),
        temps=state.temps.at[slot].set(temp),
        topps=state.topps.at[slot].set(topp),
        keys=state.keys.at[slot].set(key))


def _pick_tokens(logits, temps, topps, keys, *, sampling: bool,
                 mesh=None):
    """[B] next tokens from [B, V] logits: greedy rows argmax; sampling
    rows categorical over their own scaled, nucleus-filtered
    distribution with their OWN (already-folded) key — reusing the
    parity oracle's _filter_logits (its top_p comparison broadcasts,
    so a [B, 1] per-row mass works; topp=1 keeps everything). Shared
    by the plain decode chunk and the speculative rounds so the two
    lanes cannot drift."""
    from pyspark_tf_gke_tpu.models.causal_lm import _filter_logits

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if not sampling:
        # static: a pure-greedy pool compiles WITHOUT the per-step
        # [B, V] sort/softmax/cumsum/categorical (the dominant
        # serving path pays one argmax, as before sampling existed)
        return greedy
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if mesh is not None:
        # replicate the tiny [B, V] working set first: the nucleus
        # sort/cumsum over a tp-sharded vocab axis would otherwise
        # compile NEW cross-process collective patterns, and the
        # per-row categorical brings nothing worth sharding — the
        # replicated math keeps the sampled chunk collective-free
        # beyond what the greedy program already does (a fresh
        # communicator mid-serving deadlocked the 2-process wire).
        from jax.sharding import NamedSharding, PartitionSpec

        scaled = jax.lax.with_sharding_constraint(
            scaled, NamedSharding(mesh, PartitionSpec()))
    filtered = _filter_logits(scaled, None, topps[:, None])
    sampled = jax.vmap(jax.random.categorical)(keys, filtered)
    return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy)


def _fold_slot_keys(keys_data, n: int):
    """Fold every slot's threefry key forward by ``n`` and return
    ``(new key data [B, 2], key objects [B])`` — the per-use PRNG
    discipline of the sampling lanes."""
    keys = jax.vmap(
        lambda kd: jax.random.fold_in(
            jax.random.wrap_key_data(kd, impl="threefry2x32"), n))(
                keys_data)
    return jax.vmap(jax.random.key_data)(keys), keys


@functools.partial(
    jax.jit, static_argnames=("model", "chunk", "eos_token_id", "pad_id",
                              "sampling", "mesh"))
def _decode_chunk(model: CausalLM, params, state: SlotState, *,
                  chunk: int, eos_token_id: Optional[int],
                  pad_id: int, sampling: bool = False, mesh=None):
    """``chunk`` decode steps for ALL slots in one dispatch.

    Mirrors ``causal_lm._decode``'s emit-then-step order exactly (the
    parity oracle): emit token t from the carried logits, then run the
    model at each row's own position to produce logits t+1. Rows that
    are dead (free slot) or that hit eos keep computing — static shapes
    — but their positions freeze (no cache growth past the fill level)
    and their emitted tokens are ``pad_id``.

    Per-slot sampling: a row with ``temps > 0`` draws from its scaled,
    top-p-filtered distribution with ITS OWN key (folded forward each
    step); temp-0 rows take the argmax, and their token stream is
    bit-identical to an all-greedy chunk (the sampling lanes touch
    nothing they read)."""
    from pyspark_tf_gke_tpu.ops.quant import (dequantize_embeddings,
                                              inloop_dequantize,
                                              is_quantized)

    quantized = is_quantized(params)
    p = dequantize_embeddings(params) if quantized else params

    def pick(logits, temps, topps, keys):
        return _pick_tokens(logits, temps, topps, keys,
                            sampling=sampling, mesh=mesh)

    def step(carry, _):
        st = carry
        if sampling:
            keys = jax.vmap(
                lambda k: jax.random.fold_in(k, 1))(
                    jax.random.wrap_key_data(st.keys, impl="threefry2x32"))
            keys_data = jax.vmap(jax.random.key_data)(keys)
        else:
            keys, keys_data = None, st.keys
        tok = pick(st.last_logits, st.temps, st.topps, keys)
        # Emit BEFORE the eos latch drops `live`: the eos token itself
        # belongs to the output (generate pads WITH eos after it; the
        # host loop truncates inclusively on it).
        live = st.live
        emitted = jnp.where(live, tok, pad_id)
        if eos_token_id is not None:
            live = live & (tok != eos_token_id)
        # Dead rows replay their FROZEN position with a pad token:
        # static shape, no position growth (positions only advance
        # while live). NOT position 0: with radix prefix sharing, page
        # 0 of a slot's block table can be a page SHARED with other
        # slots and the cache — a pad-KV write there would corrupt
        # every reader. The frozen position is one past the row's last
        # real token, always inside its OWN (never-shared) allocation
        # and beyond the extent the prefix cache adopts at free time.
        step_tok = jnp.where(live, tok, pad_id)
        step_pos = st.positions
        logits, mutated = model.apply(
            {"params": inloop_dequantize(p) if quantized else p,
             "cache": st.cache},
            step_tok[:, None], decode=True, slot_decode=True,
            positions=step_pos[:, None], mutable=["cache"])
        st = st._replace(
            cache=mutated["cache"],
            positions=jnp.where(live, st.positions + 1, st.positions),
            last_logits=logits[:, 0],
            live=live,
            keys=keys_data)
        return st, emitted

    state, toks = jax.lax.scan(step, state, None, length=chunk)
    return state, toks.T  # [B, chunk]


# -- self-draft speculative decoding (in-slot draft/verify) -------------------
#
# Per slot, a cheap DRAFT model (a small companion bundle, or the target
# itself — "self-draft" — when none is configured) proposes
# ``spec_tokens`` continuation tokens, then ONE multi-query verify
# forward of the target scores all k+1 positions through the SAME
# chunked slot-decode path chunked prefill uses (paged engines: the
# ``paged_attention_chunk`` kernel — verify IS the S>1 chunk program, no
# new kernel). Accepted tokens advance each slot's fill counter;
# rejected ones roll back by simply NOT advancing it — pages are
# append-only and the position mask hides rows past the fill, so
# rollback is free and the garbage rows are overwritten by the next
# round's writes at the same positions. The acceptance rule lives in
# ``models/speculative.py`` (greedy exact; sampled lanes use the
# standard rejection rule) — ONE implementation shared with the
# standalone ``spec`` workload.
#
# The draft runs a DENSE slot cache of its own (``[num_slots,
# draft_max_seq, ...]`` rows sharing the target's per-slot fill
# counters): drafts are cheap and transient, and a paged draft pool
# would double the page-accounting surface for no bandwidth win. Draft
# contents NEVER affect correctness — a cold/garbage draft row just
# proposes tokens the verify rejects.


@functools.partial(jax.jit, static_argnames=("model", "num_slots"))
def _draft_zeros_cache(model: CausalLM, params, *, num_slots: int):
    """Fresh dense draft slot cache, built by one throwaway slot-decode
    forward (the same template trick as ``_paged_zeros_state``) and
    zeroed."""
    from pyspark_tf_gke_tpu.ops.quant import dequantize_tree

    tok = jnp.zeros((num_slots, 1), jnp.int32)
    pos = jnp.zeros((num_slots, 1), jnp.int32)
    _, mutated = model.apply(
        {"params": dequantize_tree(params)}, tok, decode=True,
        slot_decode=True, positions=pos, mutable=["cache"])
    return jax.tree.map(jnp.zeros_like, mutated["cache"])


@jax.jit
def _insert_draft_row(dcache, cache1, slot):
    """Drop a batch-1 draft prefill's cache rows into draft slot
    ``slot`` (the draft-side analog of ``_insert_slot``'s cache move;
    dense prefill caches are full ``max_seq_len`` rows, so shapes line
    up by construction)."""
    return jax.tree.map(
        lambda big, row: (jnp.maximum(big, row) if row.ndim == 0
                          else big.at[slot].set(row[0])),
        dcache, cache1)


@jax.jit
def _insert_draft_rows_batch(dcache, caches, slots):
    """Batched draft-row insert (rides the batched-admission fast
    path); pad rows carry the out-of-bounds slot sentinel and drop."""
    return jax.tree.map(
        lambda big, rows: (jnp.maximum(big, rows) if rows.ndim == 0
                           else big.at[slots].set(rows, mode="drop")),
        dcache, caches)


@functools.partial(
    jax.jit, static_argnames=("model", "draft_model", "rounds", "k",
                              "eos_token_id", "pad_id", "sampling",
                              "mesh"))
def _spec_chunk(model: CausalLM, params, draft_model: CausalLM,
                draft_params, state: SlotState, dcache, *, rounds: int,
                k: int, eos_token_id: Optional[int], pad_id: int,
                sampling: bool = False, mesh=None):
    """``rounds`` speculative draft/verify rounds for ALL slots in one
    dispatch — the spec-mode replacement for ``_decode_chunk``.

    Structure (per round, batched over slots): the carried PENDING
    token (emitted last round/entry, not yet fed) seeds a draft scan of
    k+1 single-token draft forwards proposing d_1..d_k (the final
    proposal is fed too, so the draft cache never gaps on a fully
    accepted round), then ONE (k+1)-wide verify forward of the target
    feeds [pending, d_1..d_k] at positions fill..fill+k — writing their
    K/V and scoring every position through the chunked slot-decode
    path. ``accept_and_correct`` (models/speculative.py) yields the
    accepted length and the correction/bonus token; the round emits
    [d_1..d_a, correction] (1..k+1 tokens), advances fill by exactly
    the emitted count (rejected rows beyond stay invisible — rollback
    is the fill counter), and eos anywhere in the window truncates it
    and drops the row live flag, mirroring the plain chunk's
    emit-then-latch order.

    Entry emits one token from the carried logits (exactly a plain
    step's emit) to seed the first pending; exit feeds the final
    pending token through target AND draft (one single-token step) so
    ``last_logits``/``positions`` leave in the plain chunk's invariant
    — spec and non-spec chunks interleave freely and admissions see an
    unchanged contract.

    Returns ``(state, dcache, packed)`` where ``packed`` is ONE int32
    array ``[rounds·(k+1) + 3·rounds + 2, B]`` stacking the per-round
    emission windows, their valid lengths (the host-side compaction
    gate — window tails past it are pad), the accepted/proposed counts
    (the accept-rate plane) and the entry-token/final-live rows — one
    device→host transfer (one gather on multi-process meshes) per
    collect instead of six. ``_unpack_spec`` is the host-side
    inverse."""
    from pyspark_tf_gke_tpu.models.speculative import (accept_and_correct,
                                                       emit_window)
    from pyspark_tf_gke_tpu.ops.quant import (dequantize_embeddings,
                                              inloop_dequantize,
                                              is_quantized)

    t_quant = is_quantized(params)
    p_t = dequantize_embeddings(params) if t_quant else params
    d_quant = is_quantized(draft_params)
    p_d = dequantize_embeddings(draft_params) if d_quant else draft_params
    b = state.live.shape[0]
    width = k + 1
    iota_w = jnp.arange(width, dtype=jnp.int32)

    def tparams():
        return inloop_dequantize(p_t) if t_quant else p_t

    def dparams():
        return inloop_dequantize(p_d) if d_quant else p_d

    # entry: emit one token from the carried logits (the plain chunk's
    # emit-then-step order — the eos token itself belongs to the output)
    keys_data = state.keys
    if sampling:
        keys_data, keys = _fold_slot_keys(keys_data, 1)
    else:
        keys = None
    t0 = _pick_tokens(state.last_logits, state.temps, state.topps, keys,
                      sampling=sampling, mesh=mesh)
    live0 = state.live
    entry_tok = jnp.where(live0, t0, pad_id)
    live = live0
    if eos_token_id is not None:
        live = live & (t0 != eos_token_id)
    pending = jnp.where(live, t0, pad_id)

    def round_fn(carry, _):
        cache, dc, positions, live, pending, keys_data = carry

        # 1. draft: k+1 cheap single-token forwards propose d_1..d_k
        #    (feeding pending first, then each proposal — including
        #    d_k, whose K/V a fully-accepted round needs next time)
        def dstep(dcarry, j):
            dc, cur, kd = dcarry
            feed = jnp.where(live, cur, pad_id)
            logits, mutated = draft_model.apply(
                {"params": dparams(), "cache": dc}, feed[:, None],
                decode=True, slot_decode=True,
                positions=(positions + j)[:, None], mutable=["cache"])
            lg = logits[:, 0]
            if sampling:
                kd, kk = _fold_slot_keys(kd, 3)
            else:
                kk = None
            nxt = _pick_tokens(lg, state.temps, state.topps, kk,
                               sampling=sampling, mesh=mesh)
            return (mutated["cache"], nxt, kd), (nxt, lg)

        (dc, d_last, dkd), (draft_toks, draft_logits) = jax.lax.scan(
            dstep, (dc, pending, keys_data),
            jnp.arange(k, dtype=jnp.int32))
        if sampling:
            keys_data = dkd
        drafts = draft_toks.T                              # [B, k]
        dlogits = jnp.moveaxis(draft_logits, 0, 1)         # [B, k, V]
        # feed the final proposal d_k too (cache rows only — nobody
        # reads these logits, and return_hidden skips the lm_head)
        _, mutated = draft_model.apply(
            {"params": dparams(), "cache": dc},
            jnp.where(live, d_last, pad_id)[:, None], decode=True,
            slot_decode=True, positions=(positions + k)[:, None],
            return_hidden=True, mutable=["cache"])
        dc = mutated["cache"]

        # 2. verify: ONE (k+1)-wide chunk forward writes K/V for
        #    [pending, d_1..d_k] at fill..fill+k and scores every
        #    position (paged: the paged_attention_chunk S>1 program;
        #    dead rows feed pad at frozen consecutive positions —
        #    their writes drop via the sentinel table / land past the
        #    fill mask)
        vchunk = jnp.concatenate([pending[:, None], drafts], axis=1)
        vchunk = jnp.where(live[:, None], vchunk, pad_id)
        pos_v = positions[:, None] + iota_w[None, :]
        logits_v, mutated = model.apply(
            {"params": tparams(), "cache": cache}, vchunk, decode=True,
            slot_decode=True, positions=pos_v, mutable=["cache"])
        cache = mutated["cache"]

        # 3. accept + correct (THE shared rule)
        if sampling:
            keys_data, akeys = _fold_slot_keys(keys_data, 4)
            adata = jax.vmap(jax.random.key_data)(akeys)
            a, correction = accept_and_correct(
                drafts, dlogits, logits_v, temps=state.temps,
                topps=state.topps, keys=adata, mesh=mesh)
        else:
            a, correction = accept_and_correct(drafts, dlogits, logits_v)

        # 4. emit window + eos latch + fill advance (= rollback)
        window = emit_window(drafts, correction, a)        # [B, k+1]
        if eos_token_id is not None:
            is_eos = (window == eos_token_id) & (iota_w[None]
                                                 <= a[:, None])
            any_eos = jnp.any(is_eos, axis=1)
            eos_idx = jnp.argmax(is_eos, axis=1)
            vlen = jnp.where(any_eos, eos_idx + 1, a + 1)
            newlive = live & jnp.logical_not(any_eos)
        else:
            vlen = a + 1
            newlive = live
        vlen = jnp.where(live, vlen, 0)
        emitted = jnp.where(iota_w[None] < vlen[:, None], window, pad_id)
        # fed-valid rows this round = pending + the accepted drafts
        # before any eos — exactly the emitted count (the correction is
        # emitted-not-fed, eos is emitted-not-fed; both balance out)
        positions = positions + vlen
        proposed = jnp.where(live, k, 0).astype(jnp.int32)
        accepted = jnp.where(live, a, 0).astype(jnp.int32)
        pending = jnp.where(newlive, correction, pad_id)
        return ((cache, dc, positions, newlive, pending, keys_data),
                (emitted, vlen, accepted, proposed))

    init = (state.cache, dcache, state.positions, live, pending,
            keys_data)
    ((cache, dcache, positions, live, pending, keys_data),
     (windows, wlens, accepted, proposed)) = jax.lax.scan(
        round_fn, init, None, length=rounds)

    # exit: feed the final pending token through target AND draft so the
    # carried state leaves in the plain chunk's invariant (last_logits
    # predicts the next unemitted token; every emitted token is fed)
    step_tok = jnp.where(live, pending, pad_id)
    logits, mutated = model.apply(
        {"params": tparams(), "cache": cache}, step_tok[:, None],
        decode=True, slot_decode=True, positions=positions[:, None],
        mutable=["cache"])
    _, dmut = draft_model.apply(
        {"params": dparams(), "cache": dcache}, step_tok[:, None],
        decode=True, slot_decode=True, positions=positions[:, None],
        return_hidden=True, mutable=["cache"])
    state = state._replace(
        cache=mutated["cache"],
        positions=jnp.where(live, positions + 1, positions),
        last_logits=logits[:, 0],
        live=live,
        keys=keys_data)
    packed = jnp.concatenate([
        windows.transpose(0, 2, 1).reshape(rounds * width, b),
        wlens, accepted, proposed,
        entry_tok[None].astype(jnp.int32),
        state.live.astype(jnp.int32)[None]], axis=0)
    return state, dmut["cache"], packed


def _unpack_spec(packed: np.ndarray, k: int):
    """Host-side inverse of ``_spec_chunk``'s packed output: returns
    ``(entry_tok [B], windows [rounds, k+1, B], wlens [rounds, B],
    accepted [rounds, B], proposed [rounds, B], live [B] bool)``."""
    width = k + 1
    rounds = (packed.shape[0] - 2) // (width + 3)
    wrows = rounds * width
    windows = packed[:wrows].reshape(rounds, width, -1)
    wlens = packed[wrows:wrows + rounds]
    accepted = packed[wrows + rounds:wrows + 2 * rounds]
    proposed = packed[wrows + 2 * rounds:wrows + 3 * rounds]
    return (packed[-2], windows, wlens, accepted, proposed,
            packed[-1] > 0)


class SlotDeviceState:
    """The engine's DEVICE half: the slot arrays plus the three
    replayable ops that mutate them (admit / chunk / free). Split from
    the host-side bookkeeping so multi-host serving can run the exact
    same op sequence on every process: process 0's engine announces
    each op over the serving wire and the workers' ``serve_worker_loop``
    replays it into their own ``SlotDeviceState`` — identical inputs in
    identical order is the whole SPMD contract.

    The chunk op ends with ``as_host_array`` gathers on the emitted
    tokens and live flags. That is a collective on multi-process meshes,
    so it is INSIDE the replayed op (every process participates), not a
    process-0 afterthought."""

    def __init__(self, model: CausalLM, params, num_slots: int,
                 mesh=None, draft_model: Optional[CausalLM] = None,
                 draft_params=None, spec_tokens: int = 0):
        self.model, self.params = model, params
        self.num_slots = num_slots
        self.mesh = mesh
        self.paged = bool(getattr(model.cfg, "paged_kv", False))
        self.state: Optional[SlotState] = None
        # speculative decoding: the draft pair + its dense slot cache.
        # No draft configured -> SELF-draft (the target proposes for
        # itself through a dense shadow cache — zero-config correctness
        # mode; a small companion bundle is the perf configuration).
        # Resolution is LAZY so worker replicas built before any spec
        # op (spec_tokens unknown until the first spec chunk header)
        # stay cheap.
        self.spec_tokens = int(spec_tokens)
        self.draft_model, self.draft_params = draft_model, draft_params
        self._draft_resolved = False
        self.draft_cache = None
        if draft_model is not None or self.spec_tokens:
            self._resolve_draft()

    def _resolve_draft(self) -> None:
        if self.draft_model is None:
            self.draft_model, self.draft_params = self.model, self.params
        if getattr(self.draft_model.cfg, "paged_kv", False):
            # the draft always runs the dense slot-cache layout: cheap,
            # transient, and never part of the page-pool accounting
            import dataclasses as _dc

            self.draft_model = CausalLM(
                _dc.replace(self.draft_model.cfg, kv_num_pages=None),
                self.draft_model.mesh)
        self._draft_resolved = True

    def _ensure_draft_cache(self) -> None:
        if not self._draft_resolved:
            self._resolve_draft()
        if self.draft_cache is None:
            self.draft_cache = _draft_zeros_cache(
                self.draft_model, self.draft_params,
                num_slots=self.num_slots)

    def draft_prefill_row(self, padded: np.ndarray, true_len: int,
                          slot: int) -> None:
        """Prefill the DRAFT model on the full (right-padded) prompt
        and drop its cache rows into draft slot ``slot`` — the draft's
        half of an admission (replayed on workers via the OP_CB_ADMIT
        draft payload). ``padded`` width must fit the draft's
        max_seq_len (the engine skips the call for prompts that
        don't — a cold draft row only costs acceptance, never
        correctness)."""
        with self._mesh_ctx():
            self._ensure_draft_cache()
            cache1, _ = _prefill_padded(
                self.draft_model, self.draft_params, jnp.asarray(padded),
                jnp.asarray(true_len, jnp.int32))
            self.draft_cache = _insert_draft_row(
                self.draft_cache, cache1, jnp.asarray(slot, jnp.int32))

    def draft_prefill_rows_batch(self, padded: np.ndarray, true_lens,
                                 slots) -> None:
        """Batched draft prefill for the batched-admission fast path
        (single-host only, like the target-side batch admit)."""
        k, k_pad = len(slots), padded.shape[0]
        slot_idx = np.full((k_pad,), self.num_slots, np.int32)
        slot_idx[:k] = slots
        with self._mesh_ctx():
            self._ensure_draft_cache()
            caches, _ = _prefill_padded_batch(
                self.draft_model, self.draft_params, jnp.asarray(padded),
                jnp.asarray(true_lens, jnp.int32))
            self.draft_cache = _insert_draft_rows_batch(
                self.draft_cache, caches, jnp.asarray(slot_idx))

    def spec_chunk_async(self, rounds: int, eos_token_id: Optional[int],
                         pad_id: int, sampling: bool = False,
                         k: Optional[int] = None):
        """Dispatch one speculative chunk (``rounds`` draft/verify
        rounds over all slots) WITHOUT reading back: returns a 1-tuple
        holding the PACKED int32 result array (``_unpack_spec`` is the
        host-side inverse) — the spec analog of :meth:`chunk_async`.
        ``k`` overrides the construction-time spec width (worker
        replicas learn it from each chunk header)."""
        with self._mesh_ctx():
            self._ensure_draft_cache()
            self.state, self.draft_cache, packed = _spec_chunk(
                self.model, self.params, self.draft_model,
                self.draft_params, self.state, self.draft_cache,
                rounds=rounds,
                k=int(k) if k is not None else self.spec_tokens,
                eos_token_id=eos_token_id, pad_id=pad_id,
                sampling=sampling, mesh=self.mesh)
            return (packed,)

    def fetch_tuple(self, arrays):
        """Materialize a dispatched chunk's device arrays on the host
        (any arity — a plain chunk is (tokens, live), a spec chunk ONE
        packed array; gathered on multi-process meshes so every
        process reads them)."""
        from pyspark_tf_gke_tpu.parallel.distributed import as_host_array

        with self._mesh_ctx():
            return tuple(np.asarray(as_host_array(a)) for a in arrays)

    def spec_chunk(self, rounds: int, eos_token_id: Optional[int],
                   pad_id: int, sampling: bool = False,
                   k: Optional[int] = None):
        """Dispatch + immediate readback (unpipelined spec path)."""
        return self.fetch_tuple(self.spec_chunk_async(
            rounds, eos_token_id, pad_id, sampling=sampling, k=k))

    def _mesh_ctx(self):
        return self.mesh if self.mesh is not None else (
            contextlib.nullcontext())

    def _init_state(self, cache1):
        # Inside a jit (under the caller's mesh context) so the zeros
        # come out as GLOBAL arrays on multi-process meshes — eager
        # jnp.zeros would commit to local devices and refuse to mix
        # with the mesh-spanning prefill outputs.
        if self.paged:
            # paged shapes come from the model config, not the dense
            # prefill template
            return _paged_zeros_state(self.model, self.params,
                                      num_slots=self.num_slots)
        return _zeros_state(cache1, num_slots=self.num_slots,
                            vocab=self.model.cfg.vocab_size)

    def insert(self, cache1, logits1, slot: int, fill: int,
               temperature: float = 0.0, top_p: float = 1.0,
               seed: int = 0, pages=None, n_rows: Optional[int] = None
               ) -> None:
        """Drop a prefilled/extended batch-1 tree into ``slot`` at
        ``fill`` with its sampling lane (temperature 0 = greedy).
        Paged mode additionally needs the slot's page allocation
        (``pages``, sentinel-padded) and the dense row count to
        scatter (``n_rows``, the padded bucket width)."""
        with self._mesh_ctx():
            if self.state is None:
                self.state = self._init_state(cache1)
            if self.paged:
                if pages is None or n_rows is None:
                    raise ValueError(
                        "paged insert needs pages + n_rows (the "
                        "engine allocates pages at admission)")
                self.state = _insert_slot_paged(
                    self.state, cache1, logits1,
                    np.int32(slot), np.int32(fill),
                    np.asarray(pages, np.int32),
                    np.float32(temperature), np.float32(top_p),
                    _seed_key_data(seed), n_rows=int(n_rows))
                return
            self.state = _insert_slot(
                self.state, cache1, logits1,
                np.int32(slot), np.int32(fill),
                np.float32(temperature), np.float32(top_p),
                _seed_key_data(seed))

    def admit_padded(self, padded: np.ndarray, true_len: int,
                     slot: int, temperature: float = 0.0,
                     top_p: float = 1.0, seed: int = 0,
                     pages=None) -> None:
        """Prefill a right-padded [1, S_bucket] prompt and insert it
        into ``slot`` at fill level ``true_len`` (``pages``: the
        slot's page allocation, paged mode only)."""
        with self._mesh_ctx():
            cache1, logits1 = _prefill_padded(
                self.model, self.params, np.asarray(padded),
                np.int32(true_len))
        self.insert(cache1, logits1, slot, true_len,
                    temperature=temperature, top_p=top_p, seed=seed,
                    pages=pages, n_rows=padded.shape[1])

    def admit_padded_batch(self, padded: np.ndarray, true_lens,
                           slots, samplings, pages=None) -> None:
        """ONE batched prefill + ONE batched slot scatter admits
        ``len(slots)`` requests; rows past ``len(slots)`` are shape
        padding (computed, never inserted — their scatter index is the
        out-of-bounds sentinel). Two async device ops total — no
        readback, no RTT, no per-row dispatch chatter."""
        k, k_pad = len(slots), padded.shape[0]
        slot_idx = np.full((k_pad,), self.num_slots, np.int32)
        slot_idx[:k] = slots  # pad rows -> OOB sentinel, dropped
        temps = np.zeros((k_pad,), np.float32)
        topps = np.ones((k_pad,), np.float32)
        temps[:k] = [s[0] for s in samplings]
        topps[:k] = [s[1] for s in samplings]
        # keys assemble on the HOST when every row takes
        # _seed_key_data's numpy fast path (the common case — serving
        # seeds are uint32): zero eager device ops, one transfer at
        # the jit boundary below. A row with an out-of-range seed
        # comes back as a device array, and the whole stack falls back
        # to jnp (np.asarray on it would be a synchronous
        # device->host readback per row — k+1 RTTs that the solo
        # admit path never pays; measured: batched admission LOST its
        # own win to them on the tunneled chip).
        key_rows = ([_seed_key_data(s[2]) for s in samplings]
                    + [np.zeros((2,), np.uint32)] * (k_pad - k))
        if all(isinstance(r, np.ndarray) for r in key_rows):
            keys = np.stack(key_rows)
        else:
            keys = jnp.stack([jnp.asarray(r) for r in key_rows])
        true_lens = np.asarray(true_lens, np.int32)
        # numpy args flow straight into the jitted callees — the jit
        # boundary moves them host->device in one C++ pass, cheaper
        # than a Python-level eager device_put per array
        with self._mesh_ctx():
            caches, logits = _prefill_padded_batch(
                self.model, self.params, np.asarray(padded), true_lens)
            if self.state is None:
                # _zeros_state only reads shape[1:] per leaf, so the
                # k-row tree is as good a template as a batch-1 one
                self.state = self._init_state(caches)
            if self.paged:
                if pages is None:
                    raise ValueError(
                        "paged batch insert needs per-row pages")
                self.state = _insert_slots_batch_paged(
                    self.state, caches, logits, slot_idx, true_lens,
                    np.asarray(pages, np.int32),
                    temps, topps, keys, n_rows=padded.shape[1])
            else:
                self.state = _insert_slots_batch(
                    self.state, caches, logits, slot_idx, true_lens,
                    temps, topps, keys)

    def prefill_chunk(self, padded: np.ndarray, fill: int,
                      true_len: int, row):
        """Write one chunked-prefill piece straight into the page pool
        through ``row`` (paged models only). The slot's own table row
        keeps the sentinel until :meth:`activate_slot`. Returns the
        piece's last-real-token logits as a DEVICE array (no readback
        — only the final piece's logits are ever consumed, by the
        activation)."""
        if not self.paged:
            raise ValueError(
                "prefill_chunk writes into the paged pool; dense "
                "engines stage chunked prefill on batch-1 trees")
        with self._mesh_ctx():
            if self.state is None:
                self.state = self._init_state(None)  # paged shapes come
                #   from the model config, not a prefill template
            self.state, logits1 = _paged_prefill_chunk(
                self.model, self.params, self.state, np.asarray(padded),
                np.int32(fill), np.int32(true_len), np.int32(row))
            return logits1

    def activate_slot(self, slot: int, fill: int, logits1, row,
                      temperature: float = 0.0, top_p: float = 1.0,
                      seed: int = 0) -> None:
        """Flip a chunk-admitted slot live: block-table row, fill
        level, carried logits, sampling lane (paged models only)."""
        with self._mesh_ctx():
            self.state = _activate_slot_paged(
                self.state, np.int32(slot), np.int32(row),
                np.int32(fill), logits1,
                np.float32(temperature), np.float32(top_p),
                _seed_key_data(seed))

    def copy_page(self, src: int, dst: int) -> None:
        """Clone page ``src`` into page ``dst`` across every layer's
        pool leaves (the radix cache's copy-on-write; paged models
        only). Replayed on workers via the OP_CB_ADMIT cow payload."""
        with self._mesh_ctx():
            if self.state is None:
                self.state = self._init_state(None)
            self.state = _copy_page(
                self.state, np.int32(src), np.int32(dst))

    def read_pages(self, pages) -> List[dict]:
        """Gather physical pages ``pages`` to the host: one dict per
        paged layer (k_pages/v_pages [+ scale pages]) with the page
        rows in request order (paged models only) — the export half
        of a disaggregated KV handoff. The index vector is padded to
        a power of two so the gather compiles one program per size
        class, not per transfer."""
        if not self.paged:
            raise ValueError(
                "read_pages needs the paged cache layout")
        from pyspark_tf_gke_tpu.parallel.distributed import as_host_array

        n = len(pages)
        cap = 1 << max(0, (n - 1).bit_length())
        idx = np.zeros((cap,), np.int32)
        idx[:n] = pages  # pad rows re-read page 0; sliced off below
        with self._mesh_ctx():
            if self.state is None:
                self.state = self._init_state(None)
            gathered = _gather_pages(self.state, idx)
            return [{key: np.asarray(as_host_array(leaf))[:n]
                     for key, leaf in rec.items()} for rec in gathered]

    def write_pages(self, pages, blobs) -> None:
        """Install transferred KV page rows at physical indices
        ``pages`` (paged models only) — the import half of a
        disaggregated KV handoff, replayed on workers via OP_KV_XFER.
        ``blobs`` is one dict per paged layer with ``len(pages)``
        leading rows per leaf. Padded to a power of two (sentinel
        indices drop) to bound compiled-program count."""
        if not self.paged:
            raise ValueError(
                "write_pages needs the paged cache layout")
        n = len(pages)
        cap = 1 << max(0, (n - 1).bit_length())
        idx = np.full((cap,), self.model.cfg.kv_num_pages, np.int32)
        idx[:n] = pages
        padded = []
        for rec in blobs:
            out = {}
            for key, leaf in rec.items():
                leaf = np.asarray(leaf)
                if leaf.shape[0] < cap:
                    leaf = np.concatenate(
                        [leaf, np.zeros((cap - leaf.shape[0],)
                                        + leaf.shape[1:], leaf.dtype)])
                out[key] = leaf
            padded.append(out)
        with self._mesh_ctx():
            if self.state is None:
                self.state = self._init_state(None)
            self.state = _install_pages(self.state, idx, padded)

    def chunk_async(self, chunk: int, eos_token_id: Optional[int],
                    pad_id: int, sampling: bool = False):
        """Dispatch one decode chunk over all slots (``sampling``
        static: the pure-greedy pool compiles without the sampling
        math) WITHOUT reading the result back: returns device arrays
        (tokens [B, chunk], live [B]). The caller chooses when to pay
        the device->host sync — the decode-ahead pipeline defers it one
        chunk so the readback latency overlaps the next chunk's
        compute."""
        with self._mesh_ctx():
            self.state, toks = _decode_chunk(
                self.model, self.params, self.state, chunk=chunk,
                eos_token_id=eos_token_id, pad_id=pad_id,
                sampling=sampling, mesh=self.mesh)
            return toks, self.state.live

    def fetch(self, toks, live):
        """Materialize a dispatched chunk's results on the host —
        gathered on multi-process meshes so every process can read
        them (the two-array plain-chunk case of :meth:`fetch_tuple`)."""
        return self.fetch_tuple((toks, live))

    def chunk(self, chunk: int, eos_token_id: Optional[int],
              pad_id: int, sampling: bool = False):
        """Dispatch + immediate readback (the unpipelined path)."""
        return self.fetch(*self.chunk_async(chunk, eos_token_id, pad_id,
                                            sampling=sampling))

    def free(self, slot: int) -> None:
        """Drop a slot's live flag (request finished or cancelled)."""
        if self.state is None:
            return
        with self._mesh_ctx():
            # jitted (not eager .at) so the update runs SPMD on global
            # multi-process arrays like every other replayed op; paged
            # mode also resets the slot's block-table row to the
            # sentinel (its pages are about to return to the pool)
            clear = _clear_live_paged if self.paged else _clear_live
            self.state = clear(self.state, np.int32(slot))


def _array_leaves(x):
    """Flatten a dispatched chunk's result pytree (arrays, tuples of
    arrays) into its array leaves — stdlib recursion, no jax tree
    utils, so host-array results (announce gathers) walk the same."""
    if isinstance(x, (tuple, list)):
        for y in x:
            yield from _array_leaves(y)
    elif x is not None:
        yield x


class _InflightStep:
    """One dispatched-but-unsettled chunk: the engine's explicit
    pipeline-stage state object. Carries the result handles (device
    arrays until the settle fetches them; host arrays on the
    unpipelined announce path), the slot->request SNAPSHOT the chunk
    was computed over (scheduling for the NEXT step mutates
    ``engine._slots`` freely — the settle walks this snapshot, never
    the live table), and the dispatch/retire timestamps that feed the
    device-busy interval derivation (obs/stepstats.py measurement
    model).

    ``kind`` vocabulary: ``dev`` / ``spec_dev`` hold un-fetched device
    arrays; ``host`` / ``spec_host`` hold already-gathered host arrays
    (the unpipelined announce path blocks at dispatch).

    ``t_dispatch`` is stamped at ENTRY to the dispatch call: the async
    runtime begins executing while the call is still wrapping outputs,
    so an after-return stamp undercuts the interval by however long
    the call took — on a contended 1-vCPU host the device can finish
    most of a chunk inside a slow dispatch call, collapsing its busy
    window to near zero (measured). The call-entry stamp over-counts
    by at most the pure-host prefix of one dispatch call, which is
    bounded and small; the after-return stamp under-counts by an
    unbounded contention-dependent amount. ``t_retire`` is stamped at
    the first moment the results were OBSERVED ready: a non-blocking
    ``is_ready`` poll at a step top
    (:meth:`ContinuousEngine.poll_retire`), or the fetch return when
    the data was needed while still computing. None until then."""

    __slots__ = ("kind", "a", "b", "snapshot", "size",
                 "t_dispatch", "t_retire")

    def __init__(self, kind, a, b, snapshot, size, t_dispatch):
        self.kind = kind
        self.a = a                  # tokens / packed spec results
        self.b = b                  # live flags (None for spec kinds)
        self.snapshot = snapshot    # slot -> _Request at dispatch
        self.size = size            # max tokens emitted per slot
        self.t_dispatch = float(t_dispatch)
        self.t_retire: Optional[float] = None

    def poll_ready(self) -> bool:
        """Non-blocking: True iff every result array reports ready.
        Host-kind results (no ``is_ready``) are ready by construction;
        local-only, so safe under announce (no collective)."""
        for x in _array_leaves((self.a, self.b)):
            ready = getattr(x, "is_ready", None)
            if ready is not None and not ready():
                return False
        return True


class ContinuousEngine:
    """Admit requests any time; every free KV slot is refilled at the
    next chunk boundary. ``submit`` queues, ``run_until_drained`` (or
    repeated ``step``) decodes; finished requests come back as
    ``(rid, token_list)``.

    ``announce=True`` (multi-host serving, process 0 only): every
    device op is announced over the serving wire BEFORE it runs, under
    the announce lock, so worker processes replay the identical op
    stream — see ``train/serving.py`` OP_CB_*."""

    def __init__(self, model: CausalLM, params, num_slots: int = 8,
                 chunk: int = 8, eos_token_id: Optional[int] = None,
                 pad_id: int = 0,
                 buckets: Sequence[int] = PAD_BUCKETS,
                 mesh=None, announce: bool = False,
                 prefix_cache_size: int = 0,
                 prefill_chunk: int = 0,
                 step_token_budget: int = 0,
                 pipeline_depth: int = 0,
                 adaptive_chunk: bool = False,
                 batch_admit: bool = True,
                 schedule: str = "fifo",
                 tenant_weights: Optional[Dict[str, float]] = None,
                 spec_tokens: int = 0,
                 draft_model: Optional[CausalLM] = None,
                 draft_params=None,
                 obs=None,
                 stepstats: Optional[StepStatsRing] = None,
                 peak_flops: float = 0.0):
        if num_slots < 1 or chunk < 1:
            raise ValueError("num_slots and chunk must be >= 1")
        if schedule not in ("fifo", "longest"):
            raise ValueError(
                f"schedule must be 'fifo' or 'longest', got {schedule!r}")
        # "longest" = LPT (longest-processing-time-first) admission: the
        # queue stays sorted by remaining budget, so the long requests
        # anchor the slot pool early and the short ones pack the gaps.
        # Classic makespan result; on the round-5 trail the FIFO tail —
        # one long request decoding alone while 7 slots idle — was the
        # engine's largest remaining loss vs whole-batch. Throughput
        # policy: short requests wait longer (keep "fifo" when
        # first-come latency matters more than chip utilization).
        self.schedule = schedule
        if pipeline_depth < 0:
            raise ValueError("pipeline_depth must be >= 0")
        # pipeline_depth=N ("decode-ahead"): keep up to N dispatched
        # chunks un-collected, so the device->host readback latency
        # (which DOMINATES the cycle on a remote-attached chip) overlaps
        # the next chunks' compute. Token content per request is
        # unchanged — each slot's rows depend only on its own prompt —
        # but eos frees and admissions take effect up to N chunks later
        # (bounded extra compute, discarded by the host budget clamp).
        # Depth 1 hides one readback behind one chunk's compute; deeper
        # helps when a single chunk's compute is SHORTER than the link
        # RTT (small chunks, few live slots). Multi-host (announce)
        # composes at depth 1: the chunk is announced deferred=1
        # (dispatch only) and the gathers run at a separately announced
        # OP_CB_COLLECT. Depth >= 2 is single-host only — the worker
        # replay caps its deferred-chunk window at 2 outstanding
        # (serving.py OP_CB_CHUNK), so a deeper stream would desync and
        # kill replicas.
        if pipeline_depth > 1 and announce:
            raise ValueError(
                "pipeline_depth >= 2 is single-host only (the announce "
                "replay's deferred-chunk window is depth-1 sized)")
        self.pipeline_depth = pipeline_depth
        # adaptive_chunk ("budget-aligned chunking"): size each dispatch
        # to the MINIMUM remaining token budget over the active slots
        # (bucketed to powers of two >= _MIN_ADAPTIVE_CHUNK so the jit
        # cache stays small), so a slot whose request ends at its budget
        # frees at the earliest collectable boundary instead of decoding
        # dead rows for the rest of a fixed chunk. The round-5 hardware
        # trail motivated this: at chunk 64 x depth 2 a finished request
        # wastes up to (depth+1) x chunk slot-steps before its
        # replacement admits — more than the decode-ahead saves in RTT.
        # eos-terminated requests still finish early inside a chunk
        # (budget is an upper bound); the alignment is exact for
        # budget-terminated ones.
        self.adaptive_chunk = bool(adaptive_chunk)
        # batch_admit=False disables the batched-admission fast path —
        # the A/B lever for measuring what it buys on a given link
        self.batch_admit = bool(batch_admit)
        self._n_batch_admits = 0   # requests admitted via batched ops
        self._n_solo_admits = 0    # requests admitted one at a time
        self._n_dispatched_steps = 0  # decode steps dispatched (sum of
        #   chunk sizes) — the exact device-work count, immune to link
        #   noise; see bench.py cb's device_step accounting
        from collections import deque

        # dispatched-but-unsettled chunks, oldest first (_InflightStep)
        self._inflight_q: Deque[_InflightStep] = deque()
        # admission dispatches whose device-busy interval is still
        # open: prefill + insert work is async and never collected, so
        # without these trackers every prefill's compute would be
        # measured as device IDLE. Each entry polls the post-admission
        # slot-pool state (the insert's output tree — ready only once
        # the whole prefill->insert chain ran). Bounded: a dropped
        # tracker only under-counts busy, and busy is a floor.
        self._admit_q: Deque[_InflightStep] = deque(maxlen=32)
        if prefill_chunk and prefill_chunk < 32:
            raise ValueError(
                f"prefill_chunk must be 0 (off) or >= 32, got "
                f"{prefill_chunk} (tiny pieces spend more dispatches "
                "than they save)")
        paged = bool(getattr(model.cfg, "paged_kv", False))
        if prefill_chunk and announce and not paged:
            # the DENSE piecewise extends are not on the OP_CB_* wire
            # (batch-1 staging trees live only on process 0); the paged
            # route IS — chunk progress rides OP_CB_ADMIT
            raise ValueError(
                "dense chunked prefill is single-host only (announce "
                "mode); the paged engine replays chunk progress over "
                "the wire")
        self.prefill_chunk = prefill_chunk
        if step_token_budget < 0:
            raise ValueError(
                f"step_token_budget must be >= 0, got {step_token_budget}")
        # step_token_budget ("Sarathi-style" iteration budget): cap the
        # work one engine step dispatches at ~this many tokens, split
        # between ONE prefill piece (chunked admission, up to
        # prefill_chunk tokens) and the decode chunk (live_slots x
        # steps tokens) — so a 4k-token arrival costs every streaming
        # slot a bounded stall per step instead of a whole-prompt
        # prefill. Decode steps are bucketed to powers of two (jit
        # cache: log2(chunk) programs), floored at 1 so the engine
        # always makes progress. 0 = off (fixed decode chunk).
        self.step_token_budget = int(step_token_budget)
        if prefix_cache_size and announce and not paged:
            # the DENSE prefix entries and the extend op are not on the
            # OP_CB_* wire (worker replicas would need the LRU too) —
            # single-host only. The PAGED radix cache IS on the wire:
            # cache-hit admissions replay as OP_CB_ADMIT pieces with a
            # nonzero fill (+ the COW page copy), so worker replicas
            # install identical block tables.
            raise ValueError(
                "dense prefix caching is single-host only (announce "
                "mode); the paged radix cache replays over the wire")
        self.prefix_cache = (PrefixCache(prefix_cache_size)
                             if prefix_cache_size and not paged else None)
        self.model, self.params = model, params
        # tp serving: ``params`` should already be placed
        # (shard_params_for_serving); entering the mesh context around
        # the jits lets the model's logical constraints resolve, exactly
        # as serve_generate does.
        self.mesh = mesh
        self.announce = announce
        self.num_slots, self.chunk = num_slots, chunk
        self.eos_token_id, self.pad_id = eos_token_id, pad_id
        # Default ladder adapts to the model: every standard bucket that
        # fits, plus max_seq_len itself as the top bucket — so any
        # prompt the model can serve (prompt + >=1 new token fits) has a
        # bucket, and a tiny-context model still gets one. An explicit
        # ``buckets`` argument is honored as given.
        s_max = model.cfg.max_seq_len
        if buckets is PAD_BUCKETS:
            buckets = tuple(b for b in PAD_BUCKETS if b < s_max) + (s_max,)
        self.buckets = tuple(b for b in buckets if b <= s_max)
        if not self.buckets:
            raise ValueError(
                f"no prompt bucket fits max_seq_len {s_max}")
        # -- paged KV cache: the engine owns the page pool ------------------
        self.paged = bool(getattr(model.cfg, "paged_kv", False))
        self._free_pages: List[int] = []
        self._slot_pages: Dict[int, List[int]] = {}
        # page -> refcount: slots and in-flight admissions hold one ref
        # per page they reference, the radix trie holds one per page it
        # indexes. A page is in ``_free_pages`` iff its refcount is 0 —
        # page lifetime is refcount-owned, not slot-owned, so the SAME
        # physical page can back the shared prefix of many requests.
        self._page_refs: Dict[int, int] = {}
        self.radix: Optional[RadixPrefixCache] = None
        self._peak_pages_in_use = 0
        self._n_page_alloc_failures = 0
        if self.paged:
            ps = model.cfg.kv_page_size
            if s_max % ps:
                raise ValueError(
                    f"kv_page_size {ps} must divide max_seq_len {s_max}")
            if prefix_cache_size:
                # engine-level RADIX prefix cache over the page pool:
                # completed prompts stay resident as refcounted pages
                # indexed by a token trie; admissions share the longest
                # match copy-on-write and prefill only the suffix.
                # ``prefix_cache_size`` caps the trie's resident pages
                # (clamped to the pool; LRU-evicted under pool
                # pressure either way) — NOT dense-LRU entry count.
                self.radix = RadixPrefixCache(
                    ps, min(int(prefix_cache_size),
                            model.cfg.kv_num_pages))
            # prefill rows scatter whole pages, so every admissible
            # bucket must be page-aligned
            self.buckets = tuple(b for b in self.buckets if b % ps == 0)
            if not self.buckets:
                raise ValueError(
                    f"no prompt bucket is a multiple of kv_page_size {ps}")
            self._free_pages = list(range(model.cfg.kv_num_pages))
            itemsize = 1 if model.cfg.kv_cache_quant else jnp.dtype(
                model.cfg.dtype).itemsize
            per_page = 2 * ps * model.cfg.kv_heads * model.cfg.head_dim * (
                itemsize)                                   # K + V pages
            if model.cfg.kv_cache_quant:
                per_page += 2 * ps * model.cfg.kv_heads * 4  # f32 scales
            self._page_bytes_per_layer = per_page
        self._rid = itertools.count()
        self._queue: List[_Request] = []
        # -- multi-tenant fairness: DWRR over per-tenant subqueues ----------
        # The scheduler is consulted only once TWO distinct tenants have
        # actually submitted (``_fair_active``): a single-tenant engine —
        # including every pre-tenancy caller — admits in the exact
        # FIFO/LPT order it always did, at zero extra cost per step (the
        # cb bench's FIFO-equivalent fast path).
        self._fair = DwrrScheduler(tenant_weights)
        self._first_tenant: Optional[str] = None
        self._fair_active = False
        self._slots: Dict[int, _Request] = {}
        # piecewise admission in flight (chunked prefill): at most one,
        # holding its reserved slot + the partially-built cache tree
        self._admitting: Optional[dict] = None
        self._n_finished = 0  # counter, not a list: a
        # long-lived server must not retain every prompt it ever served
        self._n_deadline_expired = 0
        # -- self-draft speculation: k draft proposals per slot-round,
        # ONE multi-query verify chunk, accepted tokens advance the
        # fill, rejected ones roll it back (see _spec_chunk) -----------
        if spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0, got {spec_tokens}")
        self.spec_tokens = int(spec_tokens)
        self._spec = self.spec_tokens > 0
        if (draft_model is not None
                and draft_model.cfg.vocab_size != model.cfg.vocab_size):
            raise ValueError(
                f"draft vocab {draft_model.cfg.vocab_size} != target "
                f"vocab {model.cfg.vocab_size}: the models must share "
                f"a tokenizer")
        self._self_draft = self._spec and draft_model is None
        self._n_spec_proposed = 0
        self._n_spec_accepted = 0
        self._n_spec_rounds = 0
        # windowed accept-rate (last 64 collected spec chunks): the
        # /loadz `spec_accept_rate` signal — a pool gone cold stops
        # advertising its warm past, like the radix hit-rate window
        self._spec_window: Deque = deque(maxlen=64)
        self._device = SlotDeviceState(
            model, params, num_slots, mesh,
            draft_model=draft_model if self._spec else None,
            draft_params=draft_params if self._spec else None,
            spec_tokens=self.spec_tokens)
        # shared metrics plane: slot occupancy + useful-token counters
        # (the cb bench's useful_tokens/sec, now scrapable live). One
        # lock op per CHUNK, not per token — hot-path safe. ``obs``
        # threads an injected registry's handles through (BundleServer
        # passes its own); default is the process registry.
        self._obs = obs if obs is not None else platform_families()
        self._obs["serve_slots_total"].set(num_slots)
        # step telemetry (obs/stepstats.py): one record per step() —
        # phase-exclusive timing + batch composition — into a bounded
        # ring exposed as GET /stepz. The serving front passes ITS
        # ring so history survives engine rebuilds; direct callers
        # (bench, tests) get a private default-size one. peak_flops
        # arms the windowed serve_mfu gauge (0 = disabled — the CPU
        # default; FLOPs/token is estimated from the model config).
        self.stepstats = (stepstats if stepstats is not None
                          else StepStatsRing())
        self.stepstats.bind(self._obs,
                            flops_per_token=flops_per_token(model.cfg),
                            peak_flops=peak_flops)
        self._step_rec = None  # the in-flight step's record (set only
        #   inside step(); _dispatch_chunk/_collect annotate through it)
        self._n_prefill_chunks = 0  # pieces processed (all admissions)
        self._n_prefill_tokens = 0  # prompt tokens actually COMPUTED
        #   by prefill forwards (pieces, buckets, extensions) — the
        #   prefix cache's whole point is keeping this ∝ unique-suffix
        #   tokens; bench/smoke read it from stats
        self._step_prefill_tokens = 0  # this step's piece tokens (the
        #   budget split's prefill half; reset at each step() top)
        self._obs["serve_prefill_inflight"].set(0)
        if self.paged:
            self._obs["serve_kv_pages_total"].set(model.cfg.kv_num_pages)
            self._update_page_gauges()

    # -- submission ------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int,
               on_tokens=None, temperature: float = 0.0,
               top_p: Optional[float] = None, seed: int = 0,
               deadline_s: Optional[float] = None,
               tenant: str = "default", span=None) -> int:
        if temperature and temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if top_p is not None and not 0 < top_p <= 1:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.model.cfg.max_seq_len:
            raise ValueError(
                f"prompt {prompt.size} + {max_new_tokens} new tokens "
                f"exceeds max_seq_len {self.model.cfg.max_seq_len}")
        chunked_route = bool(self.prefill_chunk
                             and prompt.size > self.prefill_chunk)
        if not chunked_route:
            # raises if no bucket fits; chunked-route prompts never
            # touch a bucket (pieces are prefill_chunk-wide, and the
            # dense remainder paths quantize to 32-multiples), so
            # their only bound is max_seq_len, checked above
            sb = bucket_length(prompt.size, self.buckets)
        if self.paged:
            if chunked_route:
                # chunked route: pieces write real tokens only — no
                # padded-bucket scatter, so the bound is the true
                # token extent, not the bucket's
                need = -(-(prompt.size + max_new_tokens)
                         // self.model.cfg.kv_page_size)
            else:
                need = self._pages_needed(sb, prompt.size,
                                          max_new_tokens)
            total = self.model.cfg.kv_num_pages
            if need > total:
                # with the whole pool free this request still couldn't
                # admit — queueing it would livelock run_until_drained
                raise ValueError(
                    f"request needs {need} KV pages but the pool has "
                    f"{total} (page_size "
                    f"{self.model.cfg.kv_page_size})")
        tenant = str(tenant) or "default"
        if self._first_tenant is None:
            self._first_tenant = tenant
        elif not self._fair_active and tenant != self._first_tenant:
            self._fair_active = True  # two distinct tenants seen: the
            #   DWRR picker (and its queue scan) engages from here on
        # request SHAPE onto the trace (the replay-extraction
        # contract; idempotent with the serve front's earlier stamp —
        # direct engine callers get it from here)
        annotate_request_shape(span, tenant=tenant,
                               prompt_tokens=int(prompt.size),
                               max_new_tokens=max_new_tokens,
                               deadline_s=deadline_s)
        req = _Request(next(self._rid), prompt, max_new_tokens,
                       on_tokens=on_tokens, temperature=float(temperature),
                       top_p=top_p, seed=int(seed), tenant=tenant,
                       enqueued_at=time.monotonic(),
                       deadline=(time.monotonic() + float(deadline_s)
                                 if deadline_s is not None else None),
                       span=span)
        if self.schedule == "longest":
            # insertion point keeps the queue budget-descending; ties
            # stay FIFO (stable insert after equal budgets)
            i = 0
            while (i < len(self._queue)
                   and self._queue[i].max_new_tokens >= max_new_tokens):
                i += 1
            self._queue.insert(i, req)
        else:
            self._queue.append(req)
        return req.rid

    def warm_prefix(self, prefix_ids) -> int:
        """Prefill ``prefix_ids`` once and cache the result; later
        requests whose prompt starts with it skip that prefill. Returns
        the prefix length. The prefix must leave room for at least one
        more token (a full-context prefix could never be extended).
        Paged engines route to the radix cache (the prefix lands
        straight in trie-owned pages); dense engines keep the batch-1
        LRU."""
        if self.radix is not None:
            return self._warm_prefix_paged(prefix_ids)
        if self.prefix_cache is None:
            raise ValueError("engine built without prefix_cache_size")
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        if prefix.size == 0:
            raise ValueError("empty prefix")
        if prefix.size >= self.model.cfg.max_seq_len:
            raise ValueError(
                f"prefix {prefix.size} leaves no room under max_seq_len "
                f"{self.model.cfg.max_seq_len}")
        sb = bucket_length(prefix.size, self.buckets)
        padded = right_pad(prefix, sb, self.pad_id)
        with self._device._mesh_ctx():
            cache1, logits1 = _prefill_padded(
                self.model, self.params, jnp.asarray(padded),
                jnp.asarray(prefix.size, jnp.int32))
        self._n_prefill_tokens += int(prefix.size)
        self.prefix_cache.put(prefix, cache1, logits1)
        return int(prefix.size)

    def _warm_prefix_paged(self, prefix_ids) -> int:
        """Paged ``warm_prefix``: prefill the prefix STRAIGHT into
        trie-owned pages (no slot involved) and index it, so later
        prompts starting with it admit at the match boundary. Restarts
        from the last fully-cached page when part of the prefix is
        already resident. Announce mode replays the pieces on every
        worker (OP_CB_ADMIT, never final — no slot is activated), so
        replica pools warm identically."""
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        if prefix.size == 0:
            raise ValueError("empty prefix")
        cfg = self.model.cfg
        if prefix.size >= cfg.max_seq_len:
            raise ValueError(
                f"prefix {prefix.size} leaves no room under max_seq_len "
                f"{cfg.max_seq_len}")
        ps = cfg.kv_page_size
        matched, shared, _cow = self.radix.match(
            prefix, limit=int(prefix.size), peek=True)
        if matched >= prefix.size:
            # every prefix token is already derivable from cached
            # pages (possibly ending inside a fuller page): future
            # prompts will match through them — warming adds nothing.
            # Touch the path (LRU) WITHOUT counting: a warm no-op is
            # not an admission, and repeated warms (rebuild replay,
            # periodic POST /v1/warm) must not inflate the hit rate
            # the router scores spill allowance on.
            self.radix.match(prefix, limit=int(prefix.size),
                             count=False)
            return int(prefix.size)
        fill0 = len(shared) * ps  # restart at the last FULL cached
        #   page; a partial tail match re-prefills into a fresh page
        #   that the insert below UPGRADES the tail node to
        need = -(-int(prefix.size) // ps) - len(shared)
        self._ref_pages(shared)  # pin through the pieces below
        taken = self._take_pages(need)
        if taken is None:
            self._unref_pages(shared)
            raise ValueError(
                f"KV page pool cannot hold the prefix ({need} pages "
                f"needed, {len(self._free_pages)} free after eviction)")
        row = np.full((cfg.max_pages_per_slot,), cfg.kv_num_pages,
                      np.int32)
        row[:len(shared)] = shared
        row[len(shared):len(shared) + need] = taken
        fill = fill0
        try:
            while fill < prefix.size:
                if self.prefill_chunk:
                    w = min(self.prefill_chunk, cfg.max_seq_len - fill)
                else:
                    rem = int(prefix.size) - fill
                    w = min(-(-rem // 32) * 32, cfg.max_seq_len - fill)
                piece = prefix[fill:fill + w]
                padded = right_pad(piece, w, self.pad_id)
                f0 = fill
                self._announced(
                    lambda wire, padded=padded, piece=piece, f0=f0:
                        wire.announce_cb_admit(
                            self.num_slots, padded, piece.size, 0,
                            self.eos_token_id, self.pad_id, pages=row,
                            chunk_fill=f0),
                    lambda padded=padded, piece=piece, f0=f0:
                        self._device.prefill_chunk(
                            padded, f0, piece.size, row))
                self._n_prefill_tokens += int(piece.size)
                fill += int(piece.size)
        except BaseException:
            self._unref_pages(list(shared) + taken)
            raise
        # trie refs keep the pages; the warm's own holds drop with them
        self._adopt_into_trie(prefix, list(shared) + taken,
                              holds=list(shared) + taken)
        return int(prefix.size)

    # -- disaggregated prefill/decode: KV-page handoff --------------------
    def export_prefix_pages(self, prefix_ids) -> Optional[dict]:
        """Prefill side of a disaggregated KV handoff: read the
        radix-cached pages covering ``prefix_ids`` back to the host.
        Only FULL cached pages travel (the importer's admissions
        re-prefill any tail remainder — same rule as a local radix
        hit). The pages are pinned (+1 ref) across the device gather
        so pool pressure cannot recycle them mid-read. Returns None
        when not even one full page of the prefix is cached (caller
        should warm first), else ``{token_ids, page_size, layers}``
        with one host-array dict per paged layer."""
        if self.radix is None:
            raise ValueError(
                "KV export needs the paged radix cache "
                "(prefix_cache_size > 0 on a paged model)")
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        if prefix.size == 0:
            raise ValueError("empty prefix")
        ps = self.model.cfg.kv_page_size
        _matched, shared, _cow = self.radix.match(
            prefix, limit=int(prefix.size), peek=True)
        if not shared:
            return None
        self._ref_pages(shared)
        try:
            layers = self._device.read_pages(shared)
        finally:
            self._unref_pages(shared)
        export = {
            "token_ids": [int(t) for t in prefix[:len(shared) * ps]],
            "page_size": int(ps),
            "layers": layers,
        }
        self._obs["serve_kv_xfer_export_total"].inc()
        self._obs["serve_kv_xfer_export_pages_total"].inc(len(shared))
        return export

    def import_prefix_pages(self, token_ids, layers) -> int:
        """Decode side of a disaggregated KV handoff: install the
        transferred page rows into this pool and adopt them into the
        radix trie, so ONE transfer warms every follower of the
        prefix — the importing request and all later same-prefix
        admissions hit locally. Refcount discipline mirrors
        ``_warm_prefix_paged`` (shared pages pinned through the
        install, fresh pages taken at refcount 1, everything handed
        to ``_adopt_into_trie`` with matching holds), so the chaos
        refcount audit holds on both sides of a transfer. Announce
        mode replays the page writes on every worker (OP_KV_XFER).
        Returns the number of prefix tokens now derivable from cached
        pages."""
        if self.radix is None:
            raise ValueError(
                "KV import needs the paged radix cache "
                "(prefix_cache_size > 0 on a paged model)")
        prefix = np.asarray(token_ids, np.int32).reshape(-1)
        cfg = self.model.cfg
        ps = cfg.kv_page_size
        # full pages only, and leave room for >= 1 new token (a
        # full-context prefix could never be extended)
        n = min(int(prefix.size), cfg.max_seq_len - 1) // ps
        if n <= 0:
            raise ValueError(
                f"KV transfer smaller than one page "
                f"(page_size {ps}, got {prefix.size} tokens)")
        prefix = prefix[:n * ps]
        _matched, shared, _cow = self.radix.match(
            prefix, limit=int(prefix.size), peek=True)
        if len(shared) >= n:
            # already resident: touch the path (LRU) without counting
            # — an idempotent re-import is not an admission
            self.radix.match(prefix, limit=int(prefix.size),
                             count=False)
            return int(prefix.size)
        need = n - len(shared)
        self._ref_pages(shared)  # pin through the install below
        taken = self._take_pages(need)
        if taken is None:
            self._unref_pages(shared)
            self._obs["serve_kv_xfer_failures_total"].inc()
            raise ValueError(
                f"KV page pool cannot hold the transfer ({need} pages "
                f"needed, {len(self._free_pages)} free after eviction)")
        # install only the rows BEYOND the locally-cached pages — the
        # resident prefix pages are reused, not overwritten
        blobs = [{key: np.asarray(leaf)[len(shared):n]
                  for key, leaf in rec.items()} for rec in layers]
        try:
            self._announced(
                lambda wire: wire.announce_kv_xfer(
                    self.num_slots, taken, blobs),
                lambda: self._device.write_pages(taken, blobs))
        except BaseException:
            self._unref_pages(list(shared) + taken)
            self._obs["serve_kv_xfer_failures_total"].inc()
            raise
        self._adopt_into_trie(prefix, list(shared) + taken,
                              holds=list(shared) + taken)
        self._obs["serve_kv_xfer_import_total"].inc()
        self._obs["serve_kv_xfer_import_pages_total"].inc(need)
        return int(prefix.size)

    def cancel(self, rid: int) -> bool:
        """Drop a request (abandoned client / front-side timeout): a
        queued request is removed; an active one frees its KV slot
        immediately so it stops burning decode steps. Returns True if
        the request was found. The request's span gets its terminal
        verdict HERE (outcome="cancelled") — cancellation is a state
        transition like completion/expiry, and the exactly-one-terminal
        invariant (chaos/invariants.py) counts it."""
        for i, req in enumerate(self._queue):
            if req.rid == rid:
                del self._queue[i]
                self._trace_terminal(req, "cancelled")
                return True
        for slot, req in list(self._slots.items()):
            if req.rid == rid:
                req.done = True  # an in-flight decode-ahead snapshot
                #                  must skip it at collect time
                del self._slots[slot]
                self._free_slot(slot)
                self._trace_terminal(req, "cancelled")
                return True
        if (self._admitting is not None
                and self._admitting["req"].rid == rid):
            # mid-admission: drop the partial tree (paged: return the
            # held pages); the reserved slot was never inserted/
            # activated, so nothing live to free on device
            req = self._admitting["req"]
            self._drop_admitting()
            self._trace_terminal(req, "cancelled")
            return True
        return False

    @staticmethod
    def _trace_terminal(req: _Request, outcome: str) -> None:
        """Terminal span verdict for non-delivery state transitions
        (cancel, rebuild-forced error): one emitter, None-guarded."""
        if req.span is not None:
            req.span.event("terminal", rid=req.rid, outcome=outcome,
                           new_tokens=len(req.tokens))

    # -- internals -------------------------------------------------------
    def _announced(self, announce_thunk, device_thunk):
        """THE multi-host invariant, in one place: announce the op and
        run its device work under one hold of the announce lock (the
        workers execute ops in announce order, so process 0's device
        work must happen in that same order); single-host skips
        straight to the device work."""
        if not self.announce:
            return device_thunk()
        from pyspark_tf_gke_tpu.train import serving

        with serving.mh_lock():
            announce_thunk(serving)
            return device_thunk()

    # -- page-pool bookkeeping (paged mode; host-side, process 0 only —
    # workers replay the announced allocations verbatim) ------------------
    def _pages_needed(self, s_bucket: int, true_len: int,
                      max_new: int) -> int:
        """Pages covering BOTH the padded prefill scatter (``s_bucket``
        rows land in pages) and the request's maximum token extent."""
        ps = self.model.cfg.kv_page_size
        return -(-max(int(s_bucket), int(true_len) + int(max_new)) // ps)

    def _update_page_gauges(self) -> None:
        used = self.model.cfg.kv_num_pages - len(self._free_pages)
        self._peak_pages_in_use = max(self._peak_pages_in_use, used)
        self._obs["serve_kv_pages_in_use"].set(used)
        self._obs["serve_kv_cache_bytes_per_layer"].set(
            used * self._page_bytes_per_layer)

    def _ref_pages(self, pages) -> None:
        """+1 refcount on every page (a slot, admission, or the trie
        took a reference)."""
        for p in pages:
            self._page_refs[p] = self._page_refs.get(p, 0) + 1

    def _unref_pages(self, pages) -> None:
        """-1 refcount; pages reaching zero return to the free list.
        Raises on a double free — the refcount invariant every
        admit/cancel/deadline/drain/eviction path must uphold."""
        for p in pages:
            left = self._page_refs.get(p, 0) - 1
            if left > 0:
                self._page_refs[p] = left
            elif left == 0:
                del self._page_refs[p]
                self._free_pages.append(p)
            else:
                raise RuntimeError(
                    f"KV page {p} unreferenced while already free "
                    "(double free)")
        self._update_page_gauges()

    def _adopt_into_trie(self, tokens, pages,
                         holds: Optional[List[int]] = None) -> None:
        """Index ``tokens`` over ``pages`` and move the refcounts in
        ONE place (the finish path and the warm path must never
        drift): +1 per page the trie adopts, -1 per page it releases,
        then the caller's own ``holds`` drop and the resident-page cap
        is enforced."""
        adopted, released = self.radix.insert(tokens, pages)
        if adopted:
            self._ref_pages(adopted)
        if released:
            self._unref_pages(released)
        if holds:
            self._unref_pages(holds)
        self._enforce_cache_cap()
        self._obs["serve_prefix_cache_pages"].set(
            self.radix.resident_pages)

    def _evict_cache_pages(self, n: int) -> int:
        """LRU-evict up to ``n`` trie-resident pages with no slot
        reference back to the free list (pool pressure / resident
        cap). Returns how many actually freed."""
        released = self.radix.evict(
            n, busy=lambda p: self._page_refs.get(p, 0) > 1)
        if released:
            self._obs["serve_prefix_cache_evictions_total"].inc(
                len(released))
            self._unref_pages(released)
            self._obs["serve_prefix_cache_pages"].set(
                self.radix.resident_pages)
        return len(released)

    def _enforce_cache_cap(self) -> None:
        over = (self.radix.resident_pages - self.radix.capacity
                if self.radix is not None else 0)
        if over > 0:
            self._evict_cache_pages(over)

    def _take_pages(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` fresh pages (refcount 1 each); under pressure the
        radix cache's coldest resident pages are evicted first — cache
        residency never starves a live admission. None when even that
        cannot cover ``n``."""
        if n > len(self._free_pages) and self.radix is not None:
            self._evict_cache_pages(n - len(self._free_pages))
        if n > len(self._free_pages):
            return None
        taken = [self._free_pages.pop() for _ in range(n)]
        for p in taken:
            self._page_refs[p] = 1
        self._update_page_gauges()
        return taken

    def _alloc_pages(self, n: int):
        """``(row, taken)`` — the sentinel-padded ``[max_pages_per_slot]``
        block-table row and the allocated page list — or None when the
        pool (after cache eviction) cannot cover ``n`` (the request
        stays queued; the counter increments once per failed admission
        attempt)."""
        taken = self._take_pages(n)
        if taken is None:
            self._n_page_alloc_failures += 1
            self._obs["serve_kv_page_alloc_failures_total"].inc()
            return None
        cfg = self.model.cfg
        row = np.full((cfg.max_pages_per_slot,), cfg.kv_num_pages,
                      np.int32)
        row[:n] = taken
        return row, taken

    def _note_pages(self, slot: int, taken: List[int]) -> None:
        self._slot_pages[slot] = taken
        self._update_page_gauges()

    def _release_pages(self, slot: int) -> None:
        taken = self._slot_pages.pop(slot, None)
        if taken:
            self._unref_pages(taken)

    def _free_slot(self, slot: int) -> None:
        self._announced(
            lambda wire: wire.announce_cb_free(self.num_slots, slot),
            lambda: self._device.free(slot))
        if self.paged:
            self._release_pages(slot)

    def _draft_payload(self, req: _Request):
        """``(padded [1, w], true_len)`` for the admission's draft
        prefill, or None when speculation is off or the prompt cannot
        fit the draft's context (the slot then runs on a COLD draft
        row: proposals are garbage the verify rejects — slower, never
        wrong). Width discipline mirrors the dense extend paths:
        engine buckets first, then 32-multiples, bounded by the
        draft's max_seq_len."""
        if not self._spec:
            return None
        d_max = self._device.draft_model.cfg.max_seq_len
        n = int(req.prompt.size)
        if n >= d_max:
            return None
        cands = [x for x in self.buckets if n <= x <= d_max]
        w = min(cands) if cands else min(-(-n // 32) * 32, d_max)
        return right_pad(req.prompt, w, self.pad_id), n

    def _draft_admit(self, slot: int, req: _Request) -> None:
        """Draft prefill for admission routes that are single-host by
        construction (dense prefix-hit / dense chunked / batch admit
        fallback) — announce-mode routes ride the OP_CB_ADMIT draft
        payload instead."""
        dp = self._draft_payload(req)
        if dp is not None:
            self._device.draft_prefill_row(dp[0], dp[1], slot)

    def _try_admit(self, slot: int, req: _Request) -> bool:
        """Admit ``req`` into ``slot`` — immediately, via the prefix
        cache, or by STARTING a piecewise (chunked-prefill) admission.
        Returns False only when the request needs piecewise admission
        and one is already in flight, or (paged mode) the page pool
        cannot cover it yet (FIFO holds; the request stays queued)."""
        if self.paged:
            # ONE trie walk decides the route AND seeds the admission
            # (count=False: stats wait for the final post-COW outcome;
            # the LRU touch is wanted — a queued hit keeps its path
            # warm while it waits). Safe to hand the result through:
            # nothing between here and _start_paged_admission can
            # evict (eviction only runs inside page allocation).
            m = (self.radix.match(req.prompt, count=False)
                 if self.radix is not None else (0, [], None))
            if m[0] or (self.prefill_chunk
                        and req.prompt.size - m[0]
                        > self.prefill_chunk):
                # piecewise route: chunked prefill for long prompts
                # AND every radix-cache hit (the hit installs shared
                # pages and starts the pieces at the match boundary;
                # an unchunked engine runs the whole suffix as one
                # piece)
                if self._admitting is not None:
                    return False  # one piecewise admission at a time
                self._start_paged_admission(slot, req, m)
                return True
            sb = bucket_length(req.prompt.size, self.buckets)
            alloc = self._alloc_pages(self._pages_needed(
                sb, req.prompt.size, req.max_new_tokens))
            if alloc is None:
                return False  # pool exhausted — admit at a later chunk
                #               boundary, after frees return pages
            row, taken = alloc
            padded = right_pad(req.prompt, sb, self.pad_id)
            sampling = (float(req.temperature),
                        float(req.top_p if req.top_p is not None else 1.0),
                        int(req.seed))
            dp = self._draft_payload(req)

            def device_admit():
                self._device.admit_padded(
                    padded, req.prompt.size, slot, *sampling, pages=row)
                if dp is not None:
                    self._device.draft_prefill_row(dp[0], dp[1], slot)

            try:
                # chaos: crash BETWEEN page allocation and the prefill
                # landing — the refcount-discipline audit point (the
                # except below must hand every held page back)
                chaos_fire("engine.admit", rid=req.rid)
                self._announced(
                    lambda wire: wire.announce_cb_admit(
                        self.num_slots, padded, req.prompt.size, slot,
                        self.eos_token_id, self.pad_id, sampling=sampling,
                        pages=row, draft=dp),
                    device_admit)
            except BaseException:
                # a failed admit must not leak its pages: the caller may
                # catch and keep driving this engine, and leaked pages
                # would shrink the pool below submit()'s livelock bound
                self._unref_pages(taken)
                raise
            self._n_prefill_tokens += int(req.prompt.size)
            self._note_pages(slot, taken)
            self._slots[slot] = req
            self._trace_admit(req, slot, "paged")
            if self.radix is not None:
                # this path only runs when the peek matched nothing
                # (hits route piecewise): a MISS must land in the
                # recent window too, or /loadz's hit rate would stay
                # pinned at its last warm reading while cold prompts
                # re-prefill from token 0
                self.radix.note(0)
            return True
        if (self._admitting is not None and self.prefill_chunk
                and req.prompt.size > self.prefill_chunk):
            # piecewise admission busy and this prompt MIGHT need one:
            # peek (no stats/LRU churn on every retried step) to see if
            # a prefix hit shrinks it under the threshold
            hit = (self.prefix_cache.lookup(req.prompt, peek=True)
                   if self.prefix_cache is not None else None)
            if (req.prompt.size - (hit[0] if hit is not None else 0)
                    > self.prefill_chunk):
                return False
        hit = (self.prefix_cache.lookup(req.prompt)
               if self.prefix_cache is not None else None)
        rem_size = req.prompt.size - (hit[0] if hit is not None else 0)
        if self.prefill_chunk and rem_size > self.prefill_chunk:
            if self._admitting is not None:
                return False
            # chunked prefill: long prompts admit one bounded piece per
            # step, decode chunks interleave between pieces — a 1024-
            # token arrival must not stall every streaming slot for a
            # full prefill dispatch
            if hit is not None:
                # a hit that still needs pieces for its remainder is a
                # hit all the same — the exported counters must agree
                # with the LRU's own stats
                self._obs["serve_prefix_cache_hits_total"].inc()
                self._obs["serve_prefix_cache_hit_tokens_total"].inc(
                    hit[0])
            self._admitting = {
                "slot": slot, "req": req,
                "fill": hit[0] if hit is not None else 0,
                "cache1": hit[1] if hit is not None else None,
            }
            self._trace_admit(req, slot, "chunked",
                              prefix_hit_tokens=(hit[0] if hit is not None
                                                 else 0))
            self._advance_admission()
            return True
        if hit is not None:
            self._obs["serve_prefix_cache_hits_total"].inc()
            self._obs["serve_prefix_cache_hit_tokens_total"].inc(hit[0])
            self._trace_admit(req, slot, "prefix",
                              prefix_hit_tokens=hit[0])
            self._admit_from_prefix(slot, req, *hit)
            self._draft_admit(slot, req)  # single-host path (guarded)
            self._slots[slot] = req
            return True
        sb = bucket_length(req.prompt.size, self.buckets)
        padded = right_pad(req.prompt, sb, self.pad_id)
        sampling = (float(req.temperature),
                    float(req.top_p if req.top_p is not None else 1.0),
                    int(req.seed))
        dp = self._draft_payload(req)

        def device_admit():
            self._device.admit_padded(
                padded, req.prompt.size, slot, *sampling)
            if dp is not None:
                self._device.draft_prefill_row(dp[0], dp[1], slot)

        self._announced(
            lambda wire: wire.announce_cb_admit(
                self.num_slots, padded, req.prompt.size, slot,
                self.eos_token_id, self.pad_id, sampling=sampling,
                draft=dp),
            device_admit)
        self._n_prefill_tokens += int(req.prompt.size)
        self._slots[slot] = req
        self._trace_admit(req, slot, "dense")
        return True

    def _trace_admit(self, req: _Request, slot: int, route: str,
                     **fields) -> None:
        """Span events at the moment a request wins a KV slot: the
        measured queue wait (submit → admission — the span-level answer
        to 'was it queued behind a prefill chunk?') and the admission
        route with its prefix-cache verdict. One None check for
        untraced requests."""
        sp = req.span
        if sp is None:
            return
        sp.event("queue_wait", rid=req.rid,
                 ms=round((time.monotonic() - req.enqueued_at) * 1000.0,
                          3))
        sp.event("admission", rid=req.rid, slot=slot, route=route,
                 **fields)

    def _admit_from_prefix(self, slot: int, req: _Request, fill: int,
                           cache1, logits1) -> None:
        """Admission on a prefix-cache hit: only the prompt REMAINDER
        pays a forward (one multi-token slot-decode extension of the
        cached batch-1 tree), then the extended tree drops into the
        slot. Single-host only (guarded in __init__)."""
        rem = req.prompt[fill:]
        if rem.size == 0 and logits1 is None:
            raise AssertionError(
                "prefix lookup returned an empty remainder without "
                "stored logits — lookup contract violated")
        if rem.size:
            # the remainder bucket must fit BOTH the remainder and the
            # room left above ``fill`` — a write past max_seq_len would
            # be clamped by dynamic_update_slice and land at the wrong
            # positions (submit() guarantees rem fits the room). Shape
            # discipline: prefer the engine buckets, then 32-multiples
            # (bounds distinct _extend_prefix programs), exact room
            # only as the last resort near the context limit.
            s_max = self.model.cfg.max_seq_len
            room = s_max - fill
            candidates = [b for b in self.buckets
                          if rem.size <= b <= room]
            if candidates:
                sb = min(candidates)
            else:
                quant = -(-int(rem.size) // 32) * 32
                sb = quant if quant <= room else room
            padded = np.full((1, sb), self.pad_id, np.int32)
            padded[0, :rem.size] = rem
            with self._device._mesh_ctx():
                cache1, logits1 = _extend_prefix(
                    self.model, self.params, cache1, jnp.asarray(padded),
                    jnp.asarray(fill, jnp.int32),
                    jnp.asarray(rem.size, jnp.int32))
            self._n_prefill_tokens += int(rem.size)
        if self._device.state is None:
            self._device.state = self._device._init_state(cache1)
        with self._device._mesh_ctx():
            self._device.state = _insert_slot(
                self._device.state, cache1, logits1,
                jnp.asarray(slot, jnp.int32),
                jnp.asarray(req.prompt.size, jnp.int32),
                jnp.asarray(req.temperature, jnp.float32),
                jnp.asarray(req.top_p if req.top_p is not None else 1.0,
                            jnp.float32),
                _seed_key_data(req.seed))

    def _advance_admission(self) -> None:
        """One piece of the in-flight chunked prefill: width is ALWAYS
        ``prefill_chunk`` (one compiled prefill + one compiled extend,
        regardless of prompt length); the final piece inserts the
        finished tree into the reserved slot. Tokens processed land in
        ``_step_prefill_tokens`` (via ``_note_prefill_piece``) — the
        step-budget accounting, which must also see pieces run from
        ``_try_admit`` inside ``_admit_waiting``, not only the
        step-top call."""
        if self._admitting.get("paged"):
            return self._advance_admission_paged()
        a = self._admitting
        req, fill = a["req"], a["fill"]
        # clamp the piece width to the room left under max_seq_len: a
        # full-width pad at the context limit would make
        # dynamic_update_slice CLAMP the write start below ``fill`` and
        # overwrite real prompt rows (the same hazard
        # _admit_from_prefix clamps against). Near-limit prompts pay a
        # couple of extra compiled widths; everything else stays on the
        # one full-width program.
        w = min(self.prefill_chunk,
                self.model.cfg.max_seq_len - fill)
        piece = req.prompt[fill:fill + w]
        padded = right_pad(piece, w, self.pad_id)
        with self._device._mesh_ctx():
            if a["cache1"] is None:
                cache1, logits1 = _prefill_padded(
                    self.model, self.params, jnp.asarray(padded),
                    jnp.asarray(piece.size, jnp.int32))
            else:
                cache1, logits1 = _extend_prefix(
                    self.model, self.params, a["cache1"],
                    jnp.asarray(padded), jnp.asarray(fill, jnp.int32),
                    jnp.asarray(piece.size, jnp.int32))
        a["cache1"], a["fill"] = cache1, fill + piece.size
        self._note_prefill_piece(piece.size, req)
        if a["fill"] == req.prompt.size:
            self._device.insert(
                cache1, logits1, a["slot"], req.prompt.size,
                temperature=float(req.temperature),
                top_p=float(req.top_p if req.top_p is not None else 1.0),
                seed=int(req.seed))
            self._draft_admit(a["slot"], req)  # dense chunked:
            #   single-host by construction (guarded in __init__)
            self._slots[a["slot"]] = req
            self._admitting = None

    def _note_prefill_piece(self, n: int,
                            req: Optional[_Request] = None) -> None:
        self._n_prefill_chunks += 1
        self._step_prefill_tokens += int(n)
        self._n_prefill_tokens += int(n)
        self._obs["serve_prefill_chunk_tokens"].observe(n)
        if req is not None and req.span is not None:
            req.span.event("prefill_chunk", rid=req.rid, tokens=int(n))

    def _start_paged_admission(self, slot: int, req: _Request,
                               match=None) -> None:
        """Begin a piecewise paged admission, seeded from the radix
        prefix cache when it matches: matched FULL pages are shared
        read-only (refcount +1, installed verbatim at the head of the
        admission's block-table row), a match ending inside a
        partially-filled tail page clones that page copy-on-write into
        a fresh one, and the pieces start at the match boundary — the
        prefill forward and pool writes cover the UNIQUE SUFFIX only,
        while the piece's attention reads the shared prefix pages
        through the same row."""
        cfg = self.model.cfg
        a = {"slot": slot, "req": req, "fill": 0, "paged": True,
             "row": np.full((cfg.max_pages_per_slot,), cfg.kv_num_pages,
                            np.int32),
             "pages": [], "shared": [], "cow": None}
        if self.radix is not None:
            # count=False: the effective match can still SHRINK below
            # (COW degrade under pool pressure) — the hit/miss note
            # lands after it is final, so the router's hit-rate signal
            # never reads warmer than what admissions actually skipped
            matched, shared, cow = (
                match if match is not None
                else self.radix.match(req.prompt, count=False))
            if cow is not None:
                # pin the source while the clone allocates (allocation
                # may LRU-evict resident pages — never the pinned src)
                self._ref_pages([cow[0]])
                dst = self._take_pages(1)
                if dst is None:
                    # pool can't cover the clone right now: degrade to
                    # the page boundary — full pages still share, only
                    # the tail rows recompute
                    self._unref_pages([cow[0]])
                    matched -= cow[1]
                    cow = None
                else:
                    a["cow"] = (cow[0], dst[0])
                    a["pages"].append(dst[0])
            self.radix.note(matched)
            if matched:
                self._ref_pages(shared)
                a["shared"] = shared
                a["row"][:len(shared)] = shared
                if a["cow"] is not None:
                    a["row"][len(shared)] = a["cow"][1]
                a["fill"] = matched
                self._obs["serve_prefix_cache_hits_total"].inc()
                self._obs["serve_prefix_cache_hit_tokens_total"].inc(
                    matched)
        self._trace_admit(req, slot, "paged_chunked",
                          prefix_hit_tokens=int(a["fill"]),
                          cow=a["cow"] is not None)
        self._admitting = a
        self._advance_admission()

    def _advance_admission_paged(self) -> None:
        """One piece of a PAGED chunked-prefill admission: extend the
        page allocation to cover the piece (page-by-page, as chunks
        land), run the batch-1 multi-token slot-decode forward that
        writes the piece's K/V straight into the pool, and — on the
        final piece — claim the decode extent's pages and activate the
        slot. Announce mode replays the identical piece (fill + row +
        the radix COW clone on the OP_CB_ADMIT wire) on every worker;
        a radix-hit admission's FIRST piece carries the nonzero match
        boundary as its fill, so worker block tables stay
        bit-identical. Pool dry -> the admission stalls (no piece; the
        alloc-failure counter increments once per stalled STEP, so its
        rate reads as stall duration) and retries at the next chunk
        boundary after frees."""
        a = self._admitting
        req, fill = a["req"], a["fill"]
        cfg = self.model.cfg
        ps = cfg.kv_page_size
        # same near-context-limit clamp as the dense path: a full-width
        # pad past max_seq_len would write real rows at clamped
        # positions
        if self.prefill_chunk:
            w = min(self.prefill_chunk, cfg.max_seq_len - fill)
        else:
            # radix-hit admission on an unchunked engine: the whole
            # suffix is ONE piece, width quantized to 32-multiples
            # (same compiled-program discipline as the dense extend)
            rem = req.prompt.size - fill
            w = min(-(-int(rem) // 32) * 32, cfg.max_seq_len - fill)
        piece = req.prompt[fill:fill + w]
        final = fill + piece.size == req.prompt.size
        # pages covering the piece's REAL tokens; the final piece also
        # claims the full decode extent — the engine never allocates
        # mid-decode (PR 2's zero-recompile invariant). Shared prefix
        # pages (+ the COW clone) already cover [0, match).
        covered = len(a["shared"]) + len(a["pages"])
        need_tokens = (req.prompt.size + req.max_new_tokens if final
                       else fill + piece.size)
        need = -(-need_tokens // ps) - covered
        if need > 0:
            taken = self._take_pages(need)
            if taken is None:
                self._n_page_alloc_failures += 1
                self._obs["serve_kv_page_alloc_failures_total"].inc()
                return  # stall; frees at later chunk boundaries
                #         return pages and the admission resumes
            a["row"][covered:covered + need] = taken
            a["pages"].extend(taken)
        padded = right_pad(piece, w, self.pad_id)
        sampling = (float(req.temperature),
                    float(req.top_p if req.top_p is not None else 1.0),
                    int(req.seed))
        cow = a["cow"]
        dp = self._draft_payload(req) if final else None

        def device():
            if cow is not None:
                self._device.copy_page(*cow)
            logits1 = self._device.prefill_chunk(
                padded, fill, piece.size, a["row"])
            if final:
                self._device.activate_slot(
                    a["slot"], req.prompt.size, logits1, a["row"],
                    *sampling)
                if dp is not None:
                    # the draft's context spans the WHOLE prompt (the
                    # radix match boundary included — shared pages
                    # never cross into the draft's dense rows), so the
                    # final piece carries the full prompt as the draft
                    # payload
                    self._device.draft_prefill_row(dp[0], dp[1],
                                                   a["slot"])

        try:
            self._announced(
                lambda wire: wire.announce_cb_admit(
                    self.num_slots, padded, piece.size, a["slot"],
                    self.eos_token_id, self.pad_id,
                    sampling=sampling if final else None,
                    pages=a["row"], chunk_fill=fill, final=final,
                    cow=cow, draft=dp),
                device)
        except BaseException:
            # a failed piece must not leak the admission's pages (the
            # caller may keep driving this engine)
            self._drop_admitting()
            raise
        if cow is not None:
            # the clone ran: drop the source pin (the trie's own ref
            # keeps the page alive for future matches)
            a["cow"] = None
            self._unref_pages([cow[0]])
        a["fill"] = fill + piece.size
        self._note_prefill_piece(piece.size, req)
        if final:
            self._slots[a["slot"]] = req
            self._note_pages(a["slot"], a["shared"] + a["pages"])
            self._admitting = None

    def _drop_admitting(self) -> None:
        """Abandon the in-flight piecewise admission (cancel, deadline,
        failed piece): paged admissions drop every page reference they
        hold — owned pages return to the free list, shared prefix
        pages fall back to their trie/other-slot refs, and a pending
        COW source loses its pin. The slot's table row was never set,
        so whatever the pieces wrote is unreachable and safely
        overwritten by the pages' next owner."""
        a, self._admitting = self._admitting, None
        if a is None or not a.get("paged"):
            return
        if a.get("cow") is not None:
            self._unref_pages([a["cow"][0]])
        drop = list(a.get("shared", ())) + list(a["pages"])
        if drop:
            self._unref_pages(drop)

    def _radix_insert(self, slot: int, req: _Request) -> None:
        """Index a FINISHED request's pages in the radix cache: they
        hold valid KV for prompt + emitted tokens (minus a trailing
        eos, which is emitted but never fed back — its KV row was
        never written), so a future prompt sharing that prefix skips
        its prefill. Near the context limit the insert is skipped:
        rows that are still live on device after the host-side finish
        (budget-terminated slots decode until the free lands, up to
        ``(pipeline_depth + 1) * chunk`` steps of overshoot) can reach
        position ``max_seq_len``, where the paged write's table-index
        clamp would land a garbage row at the LAST page's first
        offset — cheap to exclude, impossible to repair."""
        pages = self._slot_pages.get(slot)
        if not pages:
            return
        s_max = self.model.cfg.max_seq_len
        if (req.prompt.size + req.max_new_tokens
                + (self.pipeline_depth + 1) * self._chunk_token_bound()
                >= s_max):
            return
        toks = [int(t) for t in req.prompt] + list(req.tokens)
        if (self.eos_token_id is not None and toks
                and toks[-1] == self.eos_token_id):
            toks.pop()
        if not toks:
            return
        n_pages = -(-len(toks) // self.model.cfg.kv_page_size)
        self._adopt_into_trie(toks, pages[:n_pages])

    def _admit_batch(self, free: List[int]) -> None:
        """Batched-admission fast path (single-host): take the FIFO
        prefix of the queue that admits immediately (no prefix-cache
        hit, no chunked-prefill route) into ONE shared prompt bucket
        and prefill it all in one device op. The batch dimension is
        padded to a power of two (shape discipline: {2,4,8,...} x
        buckets compiled programs); pad rows replicate row 0 and are
        never inserted. FIFO order is preserved — the batch stops at
        the first request needing a different bucket or a special
        admission route."""
        group: List[_Request] = []
        sb0 = None
        pages_left = len(self._free_pages)
        needs: List[int] = []
        for req in self._queue:
            if len(group) >= len(free):
                break
            if (self.prefix_cache is not None
                    and self.prefix_cache.lookup(req.prompt, peek=True)):
                break  # the hit path is cheaper than a fresh prefill
            if (self.radix is not None
                    and self.radix.match(req.prompt, peek=True)[0]):
                break  # radix hit: the shared-page route skips the
                #        prefix prefill entirely — cheaper than batching
            if self.prefill_chunk and req.prompt.size > self.prefill_chunk:
                break  # piecewise route
            sb = bucket_length(req.prompt.size, self.buckets)
            if sb0 is None:
                sb0 = sb
            elif sb != sb0:
                break
            if self.paged:
                need = self._pages_needed(sb, req.prompt.size,
                                          req.max_new_tokens)
                if need > pages_left:
                    break  # pool covers the prefix only; rest stays
                    #        queued until frees return pages
                pages_left -= need
                needs.append(need)
            group.append(req)
        if len(group) < 2:
            return
        k = len(group)
        k_pad = 1 << (k - 1).bit_length()
        padded = np.full((k_pad, sb0), self.pad_id, np.int32)
        lens = np.ones((k_pad,), np.int32)
        for i, req in enumerate(group):
            padded[i, :req.prompt.size] = req.prompt
            lens[i] = req.prompt.size
        for i in range(k, k_pad):
            padded[i] = padded[0]
            lens[i] = lens[0]
        samplings = [(float(r.temperature),
                      float(r.top_p if r.top_p is not None else 1.0),
                      int(r.seed)) for r in group]
        pages_b = None
        takens: List[List[int]] = []
        if self.paged:
            cfgm = self.model.cfg
            pages_b = np.full((k_pad, cfgm.max_pages_per_slot),
                              cfgm.kv_num_pages, np.int32)
            for i, need in enumerate(needs):
                row, taken = self._alloc_pages(need)  # covered: the
                #   grouping loop already bounded the sum by the pool
                pages_b[i] = row
                takens.append(taken)
        try:
            self._device.admit_padded_batch(padded, lens, free[:k],
                                            samplings, pages=pages_b)
            if self._spec:
                d_max = self._device.draft_model.cfg.max_seq_len
                if sb0 <= d_max:
                    # the group's shared bucket fits the draft: one
                    # batched draft prefill (pad rows drop like the
                    # target-side scatter)
                    self._device.draft_prefill_rows_batch(
                        padded, lens, free[:k])
                else:
                    # bucket too wide for the draft — fall back to the
                    # per-request width discipline (skipping prompts
                    # that cannot fit at all: cold rows, never wrong)
                    for slot, req in zip(free[:k], group):
                        self._draft_admit(slot, req)
        except BaseException:
            for taken in takens:  # failed admit must not leak pages
                self._unref_pages(taken)
            raise
        self._n_prefill_tokens += sum(int(r.prompt.size) for r in group)
        for i, (slot, req) in enumerate(zip(free[:k], group)):
            self._slots[slot] = req
            self._trace_admit(req, slot, "batch")
            if self.paged:
                self._note_pages(slot, takens[i])
            if self.radix is not None:
                # batched admissions are all misses by construction
                # (the grouping loop breaks on any radix peek hit) —
                # they must cool the recent window like any other miss
                self.radix.note(0)
        del self._queue[:k]
        for req in group:
            # per-tenant admitted-token tally (stats parity with the
            # solo path; batch admit only runs single-tenant)
            self._fair.admitted_tokens[req.tenant] = (
                self._fair.admitted_tokens.get(req.tenant, 0)
                + _request_cost(req))
        self._n_batch_admits += k

    def _expire_deadlines(self) -> List[_Request]:
        """Chunk-boundary deadline enforcement: queued requests past
        their deadline never admit (a dead client must not win a KV
        slot over a live one), in-slot ones are cancelled so the slot
        frees NOW instead of after a budget of decode nobody will read,
        and a mid-admission (chunked-prefill) request drops its partial
        tree. Returns the expired requests, marked ``expired``/``done``
        — ``step`` folds them into its finished list so drivers collect
        them like completions and can tell the two apart by the flag."""
        now = time.monotonic()
        expired: List[_Request] = []
        queued_expired = 0
        keep = []
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                expired.append(req)
                queued_expired += 1
            else:
                keep.append(req)
        if expired:
            self._queue[:] = keep
        for slot, req in list(self._slots.items()):
            if req.deadline is not None and now > req.deadline:
                req.done = True  # decode-ahead snapshots skip it
                del self._slots[slot]
                self._free_slot(slot)
                expired.append(req)
        if (self._admitting is not None
                and self._admitting["req"].deadline is not None
                and now > self._admitting["req"].deadline):
            # partial cache tree dropped (paged: pages returned); the
            # reserved slot was never inserted/activated, so nothing
            # live to free on device
            expired.append(self._admitting["req"])
            self._drop_admitting()
        for req in expired:
            req.expired = True
            req.done = True
            if req.span is not None:
                # terminal verdict on the request's OWN span — emitted
                # HERE (the state transition) so direct engine callers
                # and the serve front read one consistent timeline
                req.span.event("terminal", rid=req.rid,
                               outcome="deadline",
                               new_tokens=len(req.tokens))
        if expired:
            self._n_deadline_expired += len(expired)
            self._obs["serve_request_deadline_exceeded_total"].inc(
                len(expired))
            if queued_expired:
                # expired before ANY device work — load-shedding taxonomy
                self._obs["serve_requests_rejected_total"].labels(
                    reason="deadline").inc(queued_expired)
        return expired

    @property
    def warm_capacity(self) -> int:
        """How many warmed prefixes a rebuilt engine should replay
        (the serving front retains that many token lists): the dense
        LRU's entry capacity, or a small fixed horizon for the radix
        cache (its residency is page-bounded, not entry-bounded)."""
        if self.prefix_cache is not None:
            return self.prefix_cache.capacity
        return 8 if self.radix is not None else 0

    def queue_depth(self, tenant: Optional[str] = None) -> int:
        """Requests waiting for a slot (admission queue length);
        ``tenant`` filters to one tenant's subqueue (the per-tenant
        queue-share shed check)."""
        if tenant is None:
            return len(self._queue)
        return sum(1 for r in self._queue if r.tenant == tenant)

    def queued_tokens(self, tenant: Optional[str] = None) -> int:
        """Token footprint of the admission queue: prompt + budget per
        queued request (the bound ``max_queued_tokens`` shedding uses —
        an upper bound on the KV the queue will claim). ``tenant``
        filters to one subqueue."""
        return sum(_request_cost(r) for r in self._queue
                   if tenant is None or r.tenant == tenant)

    def fail_outstanding(self, outcome: str = "error") -> List[_Request]:
        """Mark every accepted-but-undelivered request terminally
        failed: emit its ONE terminal span verdict (``outcome``:
        "error" for a rebuild after a failed/hung step, "shed" for a
        hot-swap past its drain bound) and set ``done`` so no later
        path double-delivers. Returns them — the caller (the serving
        front) settles quota refunds and fails the waiters. No device
        work happens here: this runs exactly when the engine is being
        abandoned and its device state may be mid-chunk garbage."""
        out = self.outstanding_requests()
        for req in out:
            self._trace_terminal(req, outcome)
            req.done = True
        return out

    def outstanding_requests(self) -> List[_Request]:
        """Every request the engine has accepted but not yet delivered
        (queued, in-slot, mid-admission; ``done`` ones excluded). The
        serving front settles these — quota refunds — when a failed
        device step forces an engine rebuild: their charges would
        otherwise leak with the dead engine."""
        out = [r for r in self._queue if not r.done]
        out += [r for r in self._slots.values() if not r.done]
        if (self._admitting is not None
                and not self._admitting["req"].done):
            out.append(self._admitting["req"])
        return out

    def queue_delay_ms(self) -> float:
        """Age of the OLDEST queued request in milliseconds (0 when the
        queue is empty) — the replica-side admission-delay term of the
        autoscale signal (/loadz ``queue_delay_ms``)."""
        if not self._queue:
            return 0.0
        oldest = min(r.enqueued_at for r in self._queue)
        return max(0.0, (time.monotonic() - oldest) * 1000.0)

    def tenant_stats(self) -> Dict[str, dict]:
        """Per-tenant snapshot: subqueue depth/footprint + cumulative
        admitted token cost (what the DWRR shares converge over)."""
        out: Dict[str, dict] = {}
        for r in self._queue:
            t = out.setdefault(r.tenant,
                               {"queued": 0, "queued_tokens": 0,
                                "admitted_tokens": 0})
            t["queued"] += 1
            t["queued_tokens"] += _request_cost(r)
        for tenant, adm in self._fair.admitted_tokens.items():
            t = out.setdefault(tenant,
                               {"queued": 0, "queued_tokens": 0,
                                "admitted_tokens": 0})
            t["admitted_tokens"] = int(adm)
        return out

    def _admit_waiting(self) -> None:
        reserved = (self._admitting["slot"]
                    if self._admitting is not None else None)
        free = [s for s in range(self.num_slots)
                if s not in self._slots and s != reserved]
        if (self.batch_admit and len(free) >= 2 and len(self._queue) >= 2
                and not self.announce and self._admitting is None
                and not self._fair_active):
            # the batched prefill is not on the OP_CB_* wire — announce
            # mode keeps the per-request ops (same single-host gate as
            # the prefix cache and chunked prefill). A multi-tenant
            # queue also skips it: the batch takes the QUEUE PREFIX,
            # which would let one tenant's burst jump the DWRR order.
            self._admit_batch(free)
            free = [s for s in range(self.num_slots)
                    if s not in self._slots and s != reserved]
        while free and self._queue:
            # single tenant: index 0 — the exact pre-fairness FIFO/LPT
            # order. Multi-tenant: the DWRR pick arbitrates between the
            # tenants' subqueues by weighted deficit.
            idx = self._fair.pick(self._queue) if self._fair_active else 0
            req = self._queue[idx]
            if not self._try_admit(free[0], req):
                break  # piecewise admission busy / pool dry; the pick
                #        (and its banked deficit) holds for next step
            free.pop(0)
            self._queue.pop(idx)
            if self._fair_active:
                self._fair.charge(req)
            else:
                self._fair.admitted_tokens[req.tenant] = (
                    self._fair.admitted_tokens.get(req.tenant, 0)
                    + _request_cost(req))
            self._n_solo_admits += 1

    def _chunk_token_bound(self) -> int:
        """Upper bound on per-slot fill advance from ONE dispatched
        chunk — the decode-overshoot term the near-context-limit radix
        guard uses. Plain chunks advance by at most ``chunk``; a spec
        chunk by 1 (entry) + rounds x (k+1) accepted+correction
        tokens (+1 exit feed)."""
        if not self._spec:
            return self.chunk
        k = self.spec_tokens
        return 2 + max(1, self.chunk // (k + 1)) * (k + 1)

    # -- the loop --------------------------------------------------------
    def _phase(self, name: str):
        """Phase-timing context on the in-flight step record (no-op
        outside step() — warm_prefix/cancel callers pay one attribute
        check)."""
        rec = self._step_rec
        return rec.phase(name) if rec is not None else (
            contextlib.nullcontext())

    def _effective_chunk(self) -> int:
        """Chunk size for the next dispatch. Fixed mode: ``self.chunk``.
        Adaptive mode: the largest power-of-two bucket (floored at
        ``_MIN_ADAPTIVE_CHUNK``, capped at ``self.chunk``) that does not
        overshoot the smallest remaining per-slot budget, counting steps
        already dispatched but not yet collected. Returns 0 when every
        active slot's budget is fully covered by in-flight chunks —
        dispatching more would be pure dead-row decode."""
        if not self.adaptive_chunk or not self._slots:
            return self.chunk
        pending: Dict[int, int] = {}
        for fs in self._inflight_q:
            for slot, sreq in fs.snapshot.items():
                if self._slots.get(slot) is sreq:  # not a freed slot's
                    #       stale snapshot (those rows are dead anyway)
                    pending[slot] = pending.get(slot, 0) + fs.size
        remaining = min(
            req.max_new_tokens - len(req.tokens) - pending.get(slot, 0)
            for slot, req in self._slots.items())
        if remaining <= 0:
            return 0
        c = min(remaining, self.chunk)
        b = _MIN_ADAPTIVE_CHUNK  # a sub-minimum remainder overshoots by
        while b * 2 <= c:        # < _MIN_ADAPTIVE_CHUNK steps; the
            b *= 2               # collect-side budget clamp discards it
        return min(b, self.chunk)  # an engine configured below the
        #   floor keeps its own (smaller) chunk size

    def _budget_cap(self, prefill_tokens: int) -> Optional[int]:
        """Decode steps the step-token budget leaves after this step's
        prefill piece: (budget - piece) / live_slots, bucketed DOWN to
        a power of two (jit cache: <= log2(chunk) sizes) and floored at
        1 (a piece bigger than the budget must not starve decode — the
        budget bounds the stall, it never stops token flow). None =
        budget off."""
        if not self.step_token_budget:
            return None
        live = max(len(self._slots), 1)
        steps = max((self.step_token_budget - int(prefill_tokens))
                    // live, 1)
        b = 1
        while b * 2 <= steps:
            b *= 2
        return b

    def _dispatch_chunk(self, size: int):
        """Dispatch one ``size``-step decode chunk over the current
        slots; returns the in-flight record (arrays + the slot->request
        snapshot the chunk was computed over). Announce mode,
        unpipelined: dispatch AND the as_host_array gathers run inside
        one hold of the announce lock (workers replay them as one op)
        and the record carries host arrays. Announce mode, pipelined:
        the chunk is announced deferred=1 (dispatch only, one lock
        hold) and the gathers run at the separately announced
        OP_CB_COLLECT in ``_collect`` — announced ops MAY legitimately
        sit between a deferred dispatch and its collect, on every
        process in the same order."""
        # chaos: the hung/failed DEVICE STEP fault point — a fail rule
        # raises here (the step() caller sees it exactly like a real
        # failed dispatch: the front fails in-flight requests loudly
        # and rebuilds the engine); a hang rule sleeps while the
        # driver holds its lock, which is the shape the serve-side
        # step watchdog must reap
        chaos_fire("engine.device_step")
        any_sampling = any(r.temperature > 0
                           for r in self._slots.values())
        if self._step_rec is not None:
            self._step_rec.decode_slots = max(
                self._step_rec.decode_slots, len(self._slots))
        if self._spec:
            return self._dispatch_spec(size, any_sampling)
        self._n_dispatched_steps += size
        if self.announce and not self.pipeline_depth:
            # the unpipelined announce path blocks on the readback
            # INSIDE the dispatch: carve the device sync out of the
            # dispatch phase so host overhead stays honest
            t0 = time.monotonic()
            with self._phase("device_wait"):
                toks, live = self._announced(
                    lambda wire: wire.announce_cb_chunk(
                        self.num_slots, size, self.eos_token_id,
                        self.pad_id, sampling=any_sampling),
                    lambda: self._device.chunk(
                        size, self.eos_token_id, self.pad_id,
                        sampling=any_sampling))
            fs = _InflightStep("host", toks, live, dict(self._slots),
                               size, t0)
            self._note_retired(fs, time.monotonic())
            return fs
        # t_dispatch stamps the dispatch-call ENTRY (see _InflightStep:
        # the async runtime starts executing before the call returns)
        t0 = time.monotonic()
        toks_dev, live_dev = self._announced(
            lambda wire: wire.announce_cb_chunk(
                self.num_slots, size, self.eos_token_id,
                self.pad_id, sampling=any_sampling, deferred=True),
            lambda: self._device.chunk_async(
                size, self.eos_token_id, self.pad_id,
                sampling=any_sampling))
        return _InflightStep("dev", toks_dev, live_dev,
                             dict(self._slots), size, t0)

    def _spec_rounds(self, size: int, cap: Optional[int]) -> int:
        """Draft/verify rounds for one spec dispatch. ``size`` bounds
        the EMITTED tokens per slot (the chunk semantics: fixed chunk
        or the adaptive remaining-budget size); ``cap`` (step-token
        budget) bounds the device WORK per slot — each round costs
        ~2k+2 forward tokens (k+1 draft feeds + the k+1-wide verify),
        so draft AND verify tokens both count against the budget.
        Power-of-two bucketed (jit cache discipline), floored at 1 so
        the engine always makes progress."""
        k = self.spec_tokens
        r = max(1, size // (k + 1))
        if cap is not None:
            r = min(r, max(1, cap // (2 * k + 2)))
        b = 1
        while b * 2 <= r:
            b *= 2
        return b

    def _dispatch_spec(self, rounds: int, any_sampling: bool):
        """Spec-mode dispatch: ``rounds`` draft/verify rounds over the
        current slots, on the same announce/deferred discipline as the
        plain chunk (OP_CB_CHUNK header slot 7 carries spec_tokens,
        slot 3 the round count — workers replay the identical spec
        program; accepted counts ride the collect gathers, which is
        what keeps worker fill counters/block tables bit-identical)."""
        k = self.spec_tokens
        # device-work accounting: (k+1) draft feeds + (k+1) verify
        # positions per round, + the entry/exit feeds — the spec analog
        # of "decode steps dispatched"
        self._n_dispatched_steps += rounds * (2 * k + 2) + 2
        self._n_spec_rounds += rounds
        if self._step_rec is not None:
            self._step_rec.spec_rounds += rounds
        adv = 1 + rounds * (k + 1)  # max tokens emitted per slot
        if self.announce and not self.pipeline_depth:
            t0 = time.monotonic()
            with self._phase("device_wait"):
                out = self._announced(
                    lambda wire: wire.announce_cb_chunk(
                        self.num_slots, rounds, self.eos_token_id,
                        self.pad_id, sampling=any_sampling,
                        spec_tokens=k),
                    lambda: self._device.spec_chunk(
                        rounds, self.eos_token_id, self.pad_id,
                        sampling=any_sampling))
            fs = _InflightStep("spec_host", out, None,
                               dict(self._slots), adv, t0)
            self._note_retired(fs, time.monotonic())
            return fs
        t0 = time.monotonic()  # dispatch-call entry (see _InflightStep)
        out = self._announced(
            lambda wire: wire.announce_cb_chunk(
                self.num_slots, rounds, self.eos_token_id,
                self.pad_id, sampling=any_sampling, deferred=True,
                spec_tokens=k),
            lambda: self._device.spec_chunk_async(
                rounds, self.eos_token_id, self.pad_id,
                sampling=any_sampling))
        return _InflightStep("spec_dev", out, None, dict(self._slots),
                             adv, t0)

    def _spec_slot_stream(self, spec_data, slot: int, req: _Request):
        """Compact one slot's spec-chunk output into its emitted token
        list: the entry token plus each round's window up to its valid
        length (window tails past it are pad, never emitted). Tallies
        proposed/accepted onto the request WHILE it still had budget —
        the same budget-capped stat discipline as the standalone
        drivers (overshoot rounds must not bias acceptance)."""
        entry, windows, wlens, accepted, proposed, _live = spec_data
        stream = [int(entry[slot])]
        budget = req.max_new_tokens
        prop = acc = 0
        for r in range(windows.shape[0]):
            if (int(proposed[r, slot])
                    and len(req.tokens) + len(stream) < budget):
                prop += int(proposed[r, slot])
                acc += int(accepted[r, slot])
            n = int(wlens[r, slot])
            if n:
                stream.extend(int(t) for t in windows[r, :n, slot])
        req.spec_proposed += prop
        req.spec_accepted += acc
        return np.asarray(stream, np.int64), prop, acc

    def _note_spec_stats(self, proposed: int, accepted: int) -> None:
        if not proposed:
            return
        self._n_spec_proposed += proposed
        self._n_spec_accepted += accepted
        self._spec_window.append((proposed, accepted))
        self._obs["serve_spec_proposed_total"].inc(proposed)
        self._obs["serve_spec_accepted_total"].inc(accepted)
        self._obs["serve_spec_accept_rate"].set(
            round(self.spec_accept_rate(), 4))

    def spec_accept_rate(self) -> float:
        """Windowed draft acceptance rate (last 64 collected spec
        chunks; 0.0 when speculation is off or nothing decoded yet) —
        the /loadz `spec_accept_rate` signal."""
        if not self._spec_window:
            return 0.0
        prop = sum(p for p, _ in self._spec_window)
        acc = sum(a for _, a in self._spec_window)
        return acc / prop if prop else 0.0

    def _note_retired(self, fs: _InflightStep, t_retire: float) -> None:
        """Stamp a chunk's retire timestamp (once) and feed its
        [dispatch, retire] device-busy interval to the stats ring —
        the raw input of the interval-union idle derivation."""
        if fs.t_retire is not None:
            return
        fs.t_retire = t_retire
        self.stepstats.note_device_interval(fs.t_dispatch, fs.t_retire)

    def poll_retire(self) -> None:
        """Non-blocking retire sweep: any in-flight chunk whose result
        arrays report ready gets its retire timestamp stamped NOW, so
        device-busy intervals end where the device actually went
        quiet, not where the host eventually fetched. Run at the step
        top (before this step's host work), at the step tail (after
        the settle), and by the serve driver after delivery — each a
        couple of ``is_ready`` calls. A chunk still computing is left
        alone (its settle's fetch return stamps it). Local-only
        ``is_ready`` — no collective, announce-safe."""
        now = time.monotonic()
        for fs in self._inflight_q:
            if fs.t_retire is None and fs.poll_ready():
                self._note_retired(fs, now)
        # admission trackers drain head-first (the device queue is
        # FIFO, so they complete in dispatch order)
        while self._admit_q and self._admit_q[0].poll_ready():
            self._note_retired(self._admit_q.popleft(), now)

    def _collect(self, inflight: _InflightStep) -> List[_Request]:
        """Settle one dispatched chunk: read back its results (a
        device-to-host copy that only blocks if the chunk is still
        computing) and do the host bookkeeping (token append,
        streaming callbacks, eos/budget completion, frees) for the
        slot snapshot it was computed over."""
        kind = inflight.kind
        spec_data = None
        if kind == "host":
            toks, live_host = inflight.a, inflight.b
        elif kind == "dev":
            # the serial loop's ONE blocking device sync: everything
            # outside this context is host overhead by definition
            with self._phase("device_wait"):
                toks, live_host = self._announced(
                    lambda wire: wire.announce_cb_collect(
                        self.num_slots),
                    lambda: self._device.fetch(inflight.a, inflight.b))
        elif kind == "spec_host":
            spec_data = _unpack_spec(inflight.a[0], self.spec_tokens)
            live_host = spec_data[-1]
        else:  # spec_dev: ONE packed gather at the collect
            with self._phase("device_wait"):
                packed = self._announced(
                    lambda wire: wire.announce_cb_collect(
                        self.num_slots),
                    lambda: self._device.fetch_tuple(inflight.a))
            spec_data = _unpack_spec(packed[0], self.spec_tokens)
            live_host = spec_data[-1]
        # a chunk that was still computing when its data was needed:
        # the fetch return IS the observed-ready moment
        self._note_retired(inflight, time.monotonic())
        if self._step_rec is not None:
            self._step_rec.device_busy_ms += (
                inflight.t_retire - inflight.t_dispatch) * 1000.0
        newly_done = []
        useful_tokens = 0
        chunk_prop = chunk_acc = 0
        now = time.monotonic()
        for slot, req in inflight.snapshot.items():
            if req.done:
                # freed/cancelled while this chunk was in flight (only
                # possible with decode-ahead): its rows decoded garbage
                # that nobody reads
                continue
            budget = req.max_new_tokens - len(req.tokens)
            if spec_data is not None:
                row, prop, acc = self._spec_slot_stream(
                    spec_data, slot, req)
                chunk_prop += prop
                chunk_acc += acc
                take = row[:budget]
            else:
                take = toks[slot, :budget]
            if self.eos_token_id is not None:
                hit = np.nonzero(take == self.eos_token_id)[0]
                if hit.size:
                    take = take[:hit[0] + 1]
            new_toks = [int(t) for t in take]
            useful_tokens += len(new_toks)
            if new_toks:
                # time-between-tokens, as a CLIENT sees it: the gap
                # between consecutive token deliveries to one request
                # (a chunk lands as one delivery). Prefill head-of-line
                # stalls show up here — the histogram chunked prefill
                # exists to flatten.
                if req.last_emit is not None:
                    self._obs["serve_tbt_ms"].observe(
                        (now - req.last_emit) * 1000.0)
                if req.span is not None:
                    if req.last_emit is None:
                        req.span.event(
                            "first_token", rid=req.rid,
                            ttft_ms=round(
                                (now - req.enqueued_at) * 1000.0, 3))
                    else:
                        req.span.event("tokens", rid=req.rid,
                                       n=len(new_toks))
                req.last_emit = now
            req.tokens.extend(new_toks)
            if req.on_tokens is not None and new_toks:
                try:
                    req.on_tokens(new_toks)
                except Exception:  # noqa: BLE001 — a slow/broken stream
                    # consumer must not take the whole engine down
                    logger.exception(
                        "on_tokens callback failed for request %d",
                        req.rid)
            eos_done = (self.eos_token_id is not None
                        and not live_host[slot])
            if eos_done or len(req.tokens) >= req.max_new_tokens:
                req.done = True
                newly_done.append(req)
                if req.span is not None and req.spec_proposed:
                    # per-request speculation quality on the trace
                    # (shows on /traces next to TTFT/terminal)
                    req.span.event(
                        "spec", rid=req.rid,
                        proposed=req.spec_proposed,
                        accepted=req.spec_accepted,
                        accept_rate=round(
                            req.spec_accepted / req.spec_proposed, 4))
                if req.span is not None:
                    # the span's LAST engine event: completion with the
                    # actual emitted-token count (replay extraction's
                    # output_tokens source)
                    req.span.event("terminal", rid=req.rid,
                                   outcome="ok",
                                   new_tokens=len(req.tokens))
                if self._slots.get(slot) is req:
                    del self._slots[slot]
                if self.radix is not None:
                    # completed prefixes stay resident: adopt the
                    # slot's pages into the trie BEFORE the slot's
                    # refs drop, so the next same-prefix prompt
                    # admits at the match boundary
                    self._radix_insert(slot, req)
                # slot's live flag must drop so its rows stop advancing
                self._free_slot(slot)
        self._n_finished += len(newly_done)
        if spec_data is not None:
            self._note_spec_stats(chunk_prop, chunk_acc)
        if self._step_rec is not None:
            self._step_rec.tokens_out += useful_tokens
        if useful_tokens:
            self._obs["serve_useful_tokens_total"].inc(useful_tokens)
        self._obs["serve_slots_active"].set(len(self._slots))
        self._obs["serve_queue_depth"].set(len(self._queue))
        return newly_done

    def step(self) -> List[_Request]:
        """Admit into free slots, run one decode chunk, collect tokens.
        Returns requests finished during this chunk.

        With ``pipeline_depth=N`` the collect runs up to N chunks behind
        the dispatch: the chunk launched this call is read back N calls
        later, so the device works ahead while the host waits on older
        tokens.

        Step telemetry (obs/stepstats.py): every step that does work
        closes exactly ONE record into ``self.stepstats`` — outcome
        "ok" on return, "error" when the step raises (a failed device
        dispatch, a chaos fail — the record closes in the except arm
        before the exception reaches the rebuild path), and the
        serving front relabels the record "reaped" when the watchdog
        intervened while the step hung. A step that never returns has
        an open record that never enters the ring — no half rows."""
        rec = self.stepstats.begin(queue_depth=len(self._queue))
        self._step_rec = rec
        try:
            finished = self._step_body(rec)
        except BaseException:
            self.stepstats.close(rec, outcome="error")
            raise
        finally:
            self._step_rec = None
        if rec.activity:
            self.stepstats.close(rec)
        else:
            self.stepstats.discard(rec)  # idle spin: no record
        return finished

    def _step_body(self, rec) -> List[_Request]:
        # retire sweep FIRST: chunks that finished while the host was
        # off delivering get their device-busy intervals closed at
        # this step's entry, before any of this step's host work —
        # idle is measured from here, conservatively
        self.poll_retire()
        with rec.phase("expire"):
            expired = self._expire_deadlines()
        rec.expired = len(expired)
        # per-step prefill-token accounting for the budget: pieces run
        # here AND inside _admit_waiting (a fresh admission's first
        # piece runs from _try_admit) — the counter sees both, so the
        # admission-start step's decode chunk is capped too
        self._step_prefill_tokens = 0
        pieces0 = self._n_prefill_chunks
        # admission-interval bracket: any schedule work that replaced
        # the device slot-pool state dispatched prefill+insert ops —
        # open a busy interval from the bracket entry, retired when
        # the new state's arrays report ready (poll_retire)
        state0 = self._device.state
        t_sched = time.monotonic()
        with rec.phase("schedule"):
            if self._admitting is not None:
                self._advance_admission()
            self._admit_waiting()
        if self._device.state is not state0:
            # track only the tiny `live` leaf: it comes ready with the
            # rest of the insert's outputs, and holding the full state
            # tree here would pin the superseded KV cache in device
            # memory until the tracker retires
            self._admit_q.append(_InflightStep(
                "admit", getattr(self._device.state, "live",
                                 self._device.state), None, {}, 0,
                t_sched))
        rec.prefill_pieces = self._n_prefill_chunks - pieces0
        rec.prefill_tokens = self._step_prefill_tokens
        self._obs["serve_prefill_inflight"].set(
            1 if self._admitting is not None else 0)
        cap = self._budget_cap(self._step_prefill_tokens)
        if not self.pipeline_depth:
            if not self._slots:
                return expired
            size = self._effective_chunk() or self.chunk
            if self._spec:
                # size bounds emitted tokens, cap bounds device work
                # (draft + verify both count) — _spec_rounds folds the
                # two into the round count
                size = self._spec_rounds(size, cap)
            elif cap:
                size = min(size, cap)
            with rec.phase("dispatch"):
                inflight = self._dispatch_chunk(size)
            with rec.phase("collect"):
                collected = self._collect(inflight)
            return expired + collected
        dispatched = False
        if self._slots:
            size = self._effective_chunk()
            if size and self._spec:
                size = self._spec_rounds(size, cap)
            elif size and cap:
                size = min(size, cap)
            if size:  # 0 = every slot's budget is already in flight
                with rec.phase("dispatch"):
                    self._inflight_q.append(self._dispatch_chunk(size))
                dispatched = True
        finished = list(expired)
        # Drain down to the target depth. With live slots, exactly one
        # collect runs per step (the break below) — the per-step
        # announce-op cadence stays dispatch+collect. With all slots
        # idle (everything finished/cancelled), the WHOLE backlog
        # flushes in this one call, since no later step is guaranteed.
        # A dispatch-skipped step (adaptive, budgets fully in flight)
        # must also collect one, or run_until_drained would livelock.
        while (len(self._inflight_q) > self.pipeline_depth
               or (self._inflight_q and not self._slots)
               or (self._inflight_q and not dispatched)):
            with rec.phase("collect"):
                finished += self._collect(self._inflight_q.popleft())
            if self._slots:  # collects freed slots mid-flush: stop at
                break        # target depth next call, after admissions
        # second retire sweep at the step tail: the chunk dispatched
        # THIS step often finishes during the settle above — observing
        # it here instead of at the next step's top keeps the deliver
        # phase and inter-step gap out of its busy interval
        self.poll_retire()
        return finished

    def quiesce(self) -> List[_Request]:
        """Settle EVERY in-flight chunk (device sync + full host
        bookkeeping — spans, frees, trie adoption) without
        dispatching new work; returns requests that finished in the
        flush. The pipeline-drain primitive: hot-swap and drain call
        this so no speculative chunk is abandoned mid-flight when the
        engine is about to be replaced — abandoned chunks would leak
        page refs and eat tokens the swap's successor then re-emits.
        Idempotent; a no-op on an empty pipeline. Announce mode
        announces the matching OP_CB_COLLECTs, so worker replicas
        drain their deferred window in lockstep."""
        finished: List[_Request] = []
        while self._inflight_q:
            finished += self._collect(self._inflight_q.popleft())
        return finished

    def run_until_drained(self):
        """Drive steps until queue + slots are empty; yields finished
        requests in completion order."""
        while (self._queue or self._slots or self._admitting
               or self._inflight_q):
            for req in self.step():
                yield req.rid, req.tokens

    @property
    def busy(self) -> bool:
        """Any work pending? The serving front's driver loop polls
        this every iteration — it must stay O(1) (``stats`` builds the
        full snapshot, including the windowed step-phase summary, and
        is NOT loop-cheap)."""
        return bool(self._queue or self._slots
                    or self._admitting is not None or self._inflight_q)

    @property
    def stats(self) -> dict:
        return {
            "queued": len(self._queue),
            "queued_tokens": self.queued_tokens(),
            "queue_delay_ms": round(self.queue_delay_ms(), 2),
            "tenants": self.tenant_stats(),
            "fair_active": self._fair_active,
            "active": len(self._slots),
            "finished": self._n_finished,
            "deadline_expired": self._n_deadline_expired,
            "num_slots": self.num_slots,
            "chunk": self.chunk,
            "batch_admits": self._n_batch_admits,
            "solo_admits": self._n_solo_admits,
            "dispatched_steps": self._n_dispatched_steps,
            "prefill_chunks": self._n_prefill_chunks,
            "prefill_tokens_computed": self._n_prefill_tokens,
            # windowed step-phase decomposition (obs/stepstats.py):
            # host-overhead fraction + per-phase p50/p99 — the cb
            # bench's trail block and the /loadz fraction read this
            "step_phases": self.stepstats.summary(),
            **({"step_token_budget": self.step_token_budget}
               if self.step_token_budget else {}),
            **({"spec": {
                "spec_tokens": self.spec_tokens,
                "rounds": self._n_spec_rounds,
                "proposed": self._n_spec_proposed,
                "accepted": self._n_spec_accepted,
                "accept_rate": round(
                    self._n_spec_accepted
                    / max(self._n_spec_proposed, 1), 4),
                "recent_accept_rate": round(self.spec_accept_rate(), 4),
                "self_draft": self._self_draft,
            }} if self._spec else {}),
            "admitting": (self._admitting["req"].rid
                          if self._admitting is not None else None),
            "inflight": bool(self._inflight_q),
            **({"prefix_cache": self.prefix_cache.stats}
               if self.prefix_cache is not None else
               {"prefix_cache": self.radix.stats}
               if self.radix is not None else {}),
            **({"paged": {
                "page_size": self.model.cfg.kv_page_size,
                "pages_total": self.model.cfg.kv_num_pages,
                "pages_in_use": (self.model.cfg.kv_num_pages
                                 - len(self._free_pages)),
                "peak_pages_in_use": self._peak_pages_in_use,
                "page_alloc_failures": self._n_page_alloc_failures,
                "page_bytes_per_layer": self._page_bytes_per_layer,
            }} if self.paged else {}),
        }
