"""Shared run scaffolding for the training entry points (cli.py,
bert_finetune.py): the pieces every entry repeats — host-local batch
sizing, init-sample preparation, checkpoint setup/restore/finalize, and
the heartbeat/recovery plumbing from train/resilience.py."""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import numpy as np

from pyspark_tf_gke_tpu.train.checkpoint import CheckpointManager, save_history
from pyspark_tf_gke_tpu.train.resilience import Heartbeat


def local_batch_size(global_batch: int) -> int:
    """Per-host batch from the GLOBAL batch size (reference semantics:
    batch flags are global; each host feeds its slice)."""
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(
            f"global batch {global_batch} not divisible by {n_proc} hosts"
        )
    return global_batch // n_proc


def make_checkpoint(
    output_dir: str,
    every_steps: int,
    state,
    resume: bool,
):
    """Build the CheckpointManager under ``output_dir`` and restore the
    latest step when resuming. Returns (manager, possibly-restored state)."""
    ckpt = CheckpointManager(
        os.path.join(output_dir, "checkpoints"), every_steps=every_steps
    )
    if resume and ckpt.latest_step() is not None:
        state = ckpt.restore(state)
    return ckpt, state


def finalize_run(ckpt: CheckpointManager, state, history: Dict, output_dir: str) -> None:
    """Terminal save: checkpoint + history.json (the reference's
    model.save + history dump, train_tf_ps.py:674-679)."""
    ckpt.save(state, history)
    save_history(output_dir, history)


def make_heartbeat(
    output_dir: str, every_steps: int, path: str = ""
) -> Optional[Heartbeat]:
    if not every_steps:
        return None
    return Heartbeat(path or os.path.join(output_dir, "heartbeat.json"), every_steps)
