"""Shared run scaffolding for the training entry points (cli.py,
bert_finetune.py): the pieces every entry repeats — host-local batch
sizing, checkpoint setup/restore/finalize, run-notes artifacts, and the
heartbeat plumbing from train/resilience.py."""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import numpy as np

from pyspark_tf_gke_tpu.train.checkpoint import CheckpointManager, save_history
from pyspark_tf_gke_tpu.train.resilience import Heartbeat
from pyspark_tf_gke_tpu.utils.fs import fs_write_text, is_remote


# THE optimizer list: every CLI's --optimizer choices come from here so
# a new family lands in all entry points at once (cli, lm_pretrain,
# bert_finetune each used to copy-paste it and drift).
OPTIMIZERS = ("adam", "adamw", "sgd", "momentum", "lamb", "adafactor")


def make_optimizer(
    learning_rate: float,
    schedule: str = "constant",
    total_steps: int = 0,
    warmup_steps: int = 0,
    optimizer: str = "adam",
    weight_decay: float = 0.0,
    momentum: float = 0.9,
    grad_clip_norm: float = 0.0,
):
    """Optimizer factory: adam | adamw | sgd | momentum | lamb |
    adafactor with an
    optax LR schedule (constant | cosine | warmup_cosine) and optional
    global-norm gradient clipping. (The reference uses bare constant-LR
    Adam, train_tf_ps.py:339,606; adamw+warmup_cosine is the standard
    recipe for the BERT config, lamb for large-batch pretraining.)"""
    import optax

    if schedule not in ("constant", "cosine", "warmup_cosine"):
        raise ValueError(
            f"unknown lr schedule {schedule!r}; use constant | cosine | warmup_cosine"
        )
    if weight_decay and optimizer not in ("adamw", "lamb", "adafactor"):
        raise ValueError(
            f"weight_decay={weight_decay} is ignored by optimizer "
            f"{optimizer!r} — use adamw, lamb or adafactor (or set "
            "weight_decay=0)"
        )
    if warmup_steps and schedule != "warmup_cosine":
        raise ValueError(
            f"warmup_steps={warmup_steps} is ignored by schedule "
            f"{schedule!r} — use warmup_cosine (or set warmup_steps=0)"
        )
    if schedule != "constant" and total_steps <= 0:
        raise ValueError(
            f"lr schedule {schedule!r} needs total_steps > 0 (a decay over 0 "
            "steps would pin the learning rate at ~0 for the whole run)"
        )
    if schedule == "constant":
        lr = learning_rate
    elif schedule == "cosine":
        lr = optax.cosine_decay_schedule(learning_rate, total_steps)
    elif schedule == "warmup_cosine":
        lr = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, max(warmup_steps, 1),
            max(total_steps, warmup_steps + 1),
        )

    def decay_mask(params):
        # Standard BERT/LAMB recipe: decay matrices/embeddings only —
        # never biases or LayerNorm scales (all 1-D leaves).
        import jax as _jax

        return _jax.tree.map(lambda p: _jax.numpy.ndim(p) >= 2, params)

    if optimizer == "adam":
        tx = optax.adam(lr)
    elif optimizer == "adamw":
        tx = optax.adamw(lr, weight_decay=weight_decay, mask=decay_mask)
    elif optimizer == "sgd":
        tx = optax.sgd(lr)
    elif optimizer == "momentum":
        tx = optax.sgd(lr, momentum=momentum, nesterov=True)
    elif optimizer == "lamb":
        tx = optax.lamb(lr, weight_decay=weight_decay, mask=decay_mask)
    elif optimizer == "adafactor":
        # the TPU-idiomatic memory-efficient choice (t5x's default):
        # factored second moments store O(rows+cols) per matrix instead
        # of Adam's O(rows*cols) — at h768 BERT scale the optimizer
        # state drops ~2x, which the analytic roofline
        # (tools/roofline.py) counts directly against the per-step HBM
        # stream the flagship is bound on.
        tx = optax.adafactor(lr, weight_decay_rate=weight_decay or None,
                             weight_decay_mask=(decay_mask if weight_decay
                                                else None))
    else:
        raise ValueError(
            f"unknown optimizer {optimizer!r}; use " + " | ".join(OPTIMIZERS)
        )
    if grad_clip_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    return tx


def local_batch_size(global_batch: int) -> int:
    """Per-host batch from the GLOBAL batch size (reference semantics:
    batch flags are global; each host feeds its slice)."""
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(
            f"global batch {global_batch} not divisible by {n_proc} hosts"
        )
    return global_batch // n_proc


def make_checkpoint(
    output_dir: str,
    every_steps: int,
    state,
    resume: bool,
    async_save: bool = False,
):
    """Build the CheckpointManager under ``output_dir`` and restore the
    latest step when resuming. Returns (manager, possibly-restored state)."""
    ckpt = CheckpointManager(
        os.path.join(output_dir, "checkpoints"), every_steps=every_steps,
        async_save=async_save,
    )
    if resume and ckpt.latest_step() is not None:
        state = ckpt.restore(state)
    return ckpt, state


def finalize_run(ckpt: CheckpointManager, state, history: Dict, output_dir: str,
                 model_name: str = "model") -> None:
    """Terminal save: checkpoint + history.json (the reference's
    model.save + history dump, train_tf_ps.py:674-679) + run notes."""
    ckpt.save(state, history)
    ckpt.wait()  # terminal save must be durable before the process exits
    save_history(output_dir, history)
    save_run_notes(output_dir, model_name, state, history)


def save_run_notes(output_dir: str, model_name: str, state, history: Dict) -> str:
    """``<model_name>.txt`` run notes — the analog of the reference's
    ``tf-model/150-320-by-256-B1-model.txt`` artifacts (param count/size,
    hardware, epochs, final metrics)."""
    path = os.path.join(output_dir, f"{model_name}.txt")
    if jax.process_index() != 0:
        return path
    leaves = jax.tree.leaves(state.params)
    n_params = sum(int(np.prod(l.shape)) for l in leaves)
    n_bytes = sum(int(np.prod(l.shape)) * l.dtype.itemsize for l in leaves)
    devices = jax.devices()
    lines = [
        f"model: {model_name}",
        f"total params: {n_params:,}",
        f"size: {n_bytes / (1 << 20):.2f} MB",
        f"devices: {len(devices)}x {devices[0].platform}"
        + (f" ({devices[0].device_kind})" if hasattr(devices[0], "device_kind") else ""),
        f"processes: {jax.process_count()}",
        f"final step: {int(jax.device_get(state.step))}",
        f"epochs recorded: {len(history.get('loss', []))}",
    ]
    for key, vals in sorted(history.items()):
        if vals:
            lines.append(f"final {key}: {vals[-1]:.6g}")
    fs_write_text(path, "\n".join(lines) + "\n")
    return path


def make_heartbeat(
    output_dir: str, every_steps: int, path: str = ""
) -> Optional[Heartbeat]:
    if not every_steps:
        return None
    if not path:
        # heartbeats must be node-local (age probes need local mtime;
        # a per-step gs:// write would be absurd) — when the artifact
        # dir is remote, default to /tmp like the k8s manifests do.
        # Per-process in BOTH defaults: with a shared file a hung
        # process hides behind any live peer's beats (local
        # multi-process runs are exactly the fake-slice test shape).
        path = ("/tmp/tpu-heartbeat-{process_index}.json"
                if is_remote(output_dir)
                else os.path.join(output_dir,
                                  "heartbeat-{process_index}.json"))
    return Heartbeat(path, every_steps)
