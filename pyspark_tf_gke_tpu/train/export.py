"""Serving-bundle export/load: the framework's terminal model artifact.

The reference's terminal artifact is a saved Keras model plus sidecar
JSONs (``train_tf_ps.py:674-679``, ``tf-model/*``); the TPU-native
analog is a **serving bundle**: one directory holding

* ``config.json``   — the model architecture (CausalLMConfig fields,
  minus the dtype, which is serialized by name) + bundle metadata
  (quantized or not, tokenizer spec);
* ``params/``       — an orbax snapshot of the param tree, either dense
  or weight-only int8 (``ops/quant.py`` QTensor leaves — a pytree, so
  orbax handles it natively and the artifact shrinks ~4×).

``load_serving_bundle`` reconstructs the model and params ready for
``train/serving.py`` placement on any mesh. No framework-pickle, no
code in the artifact — config is data, weights are arrays.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from pyspark_tf_gke_tpu.models.causal_lm import CausalLM, CausalLMConfig
from pyspark_tf_gke_tpu.ops.quant import is_quantized, quantize_tree

_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
           "float16": jnp.float16}


def _qtensor_paths(params) -> list:
    """Sorted keystr paths of every QTensor leaf."""
    from pyspark_tf_gke_tpu.ops.quant import QTensor

    flat, _ = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=lambda l: isinstance(l, QTensor))
    return sorted(jax.tree_util.keystr(path) for path, leaf in flat
                  if isinstance(leaf, QTensor))


def _qtensor_scale_shapes(params) -> dict:
    """keystr path → scale shape for every QTensor leaf. Recorded in the
    bundle so the loader rebuilds the exact abstract (per-column kernels
    carry ``(cols,)`` scales, per-row embedding tables ``(rows, 1)``,
    caller-quantized trees whatever the caller chose) without guessing
    from the path."""
    from pyspark_tf_gke_tpu.ops.quant import QTensor

    flat, _ = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=lambda l: isinstance(l, QTensor))
    return {jax.tree_util.keystr(path): list(leaf.scale.shape)
            for path, leaf in flat if isinstance(leaf, QTensor)}


def export_serving_bundle(
    cfg: CausalLMConfig,
    params: Any,
    out_dir: str,
    quantize: bool = True,
    tokenizer_spec: str = "byte",
    quantize_min_size: int = 4096,
    extra_meta: Optional[dict] = None,
) -> str:
    """Write a self-contained serving bundle. Returns ``out_dir``.

    ``extra_meta``: caller annotations merged into ``config.json``
    (reserved keys win) — the pipeline coordinator stamps
    ``pipeline_generation`` here so a replica serving the bundle
    advertises that generation on ``/loadz``."""
    os.makedirs(out_dir, exist_ok=True)
    if quantize and not is_quantized(params):
        params = jax.jit(
            lambda p: quantize_tree(p, min_size=quantize_min_size))(params)

    cfg_dict = dataclasses.asdict(cfg)
    cfg_dict["dtype"] = jnp.dtype(cfg.dtype).name
    meta = {
        **(extra_meta or {}),
        "format": "pyspark_tf_gke_tpu.serving_bundle.v1",
        "model": "causal_lm",
        "quantized": bool(is_quantized(params)),
        # The exact QTensor leaf paths, recorded so the loader rebuilds
        # the same pytree no matter how the tree was quantized (caller-
        # quantized trees included — a min_size alone couldn't say).
        "quantized_paths": _qtensor_paths(params),
        "quantized_scale_shapes": _qtensor_scale_shapes(params),
        "tokenizer": tokenizer_spec,
        "config": cfg_dict,
    }
    if jax.process_index() == 0:
        with open(os.path.join(out_dir, "config.json"), "w") as fh:
            json.dump(meta, fh, indent=2)

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(os.path.abspath(out_dir), "params"), params,
               force=True)
    ckptr.wait_until_finished()
    ckptr.close()
    return out_dir


def load_serving_bundle(bundle_dir: str) -> Tuple[CausalLM, Any, dict]:
    """Load ``(model, params, meta)`` from an exported bundle. The
    params come back with the exact pytree the bundle was saved with
    (QTensor leaves included) — pass them through
    ``train/serving.shard_params_for_serving`` to place on a mesh."""
    with open(os.path.join(bundle_dir, "config.json")) as fh:
        meta = json.load(fh)
    if meta.get("model") != "causal_lm":
        raise ValueError(f"unsupported bundle model {meta.get('model')!r}")

    cfg_dict = dict(meta["config"])
    cfg_dict["dtype"] = _DTYPES[cfg_dict["dtype"]]
    cfg = CausalLMConfig(**cfg_dict)
    model = CausalLM(cfg)

    # Abstract target with the same pytree (incl. QTensor nodes) so
    # orbax restores structure-exactly: re-init abstractly, then
    # quantize exactly the leaves the bundle recorded as QTensors.
    from flax import linen as nn

    from pyspark_tf_gke_tpu.ops.quant import quantize_tensor

    sample = jnp.zeros((1, 8), jnp.int32)
    abstract = jax.eval_shape(
        lambda: nn.meta.unbox(model.init(jax.random.PRNGKey(0), sample)["params"]))
    qpaths = set(meta.get("quantized_paths", []))
    if qpaths:
        from pyspark_tf_gke_tpu.ops.quant import QTensor, is_embedding_path

        scale_shapes = meta.get("quantized_scale_shapes", {})

        def requantize_with(path, leaf, embed_axis0: bool):
            key = jax.tree_util.keystr(path)
            if key not in qpaths:
                return leaf
            if key in scale_shapes:
                # the bundle records each scale's exact shape — rebuild
                # the abstract from it so orbax validation matches
                # whatever granularity the export used
                return QTensor(
                    jax.ShapeDtypeStruct(leaf.shape, jnp.int8),
                    jax.ShapeDtypeStruct(
                        tuple(scale_shapes[key]), jnp.float32),
                    leaf.dtype)
            # Bundles from before scale shapes were recorded: most are
            # uniformly per-column, but a brief window quantized
            # embedding tables per-row — build_abstract covers both and
            # the loader below retries with the other interpretation.
            axis = 0 if (embed_axis0 and is_embedding_path(path)) else -1
            return jax.eval_shape(lambda l: quantize_tensor(l, axis=axis),
                                  leaf)

        def build_abstract(embed_axis0: bool):
            return jax.tree_util.tree_map_with_path(
                lambda p, l: requantize_with(p, l, embed_axis0), abstract)

        abstract_candidates = ([build_abstract(False)] if scale_shapes else
                               [build_abstract(False), build_abstract(True)])
    elif meta.get("quantized"):
        # Back-compat: bundles written before quantized_paths were
        # recorded carry only the export-side min_size threshold — and
        # predate per-row embedding scales, so every recorded scale is
        # the legacy per-column (cols,) shape.
        min_size = int(meta.get("quantize_min_size", 4096))

        def legacy_q(leaf):
            if (len(leaf.shape) == 2
                    and int(np.prod(leaf.shape)) >= min_size
                    and jnp.issubdtype(leaf.dtype, jnp.floating)):
                return jax.eval_shape(quantize_tensor, leaf)
            return leaf

        abstract_candidates = [jax.tree.map(legacy_q, abstract)]
    else:
        abstract_candidates = [abstract]

    if jax.process_count() > 1:
        # Multi-process restore: orbax refuses sharding-less abstract
        # arrays here ("sharding ... should be specified [and] concrete").
        # Every process restores the FULL array onto its own CPU backend
        # device — host RAM, NOT an accelerator: a model that needs tp
        # to fit would OOM a single chip's HBM before
        # shard_params_for_serving ever placed its shards.
        try:
            host_dev = jax.local_devices(backend="cpu")[0]
        except RuntimeError:  # pragma: no cover - cpu backend always exists
            host_dev = jax.local_devices()[0]
        local = jax.sharding.SingleDeviceSharding(host_dev)

        def pin(leaf):
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                            sharding=local)
            return leaf

        abstract_candidates = [jax.tree.map(pin, c)
                               for c in abstract_candidates]

    ckptr = ocp.StandardCheckpointer()
    try:
        params_path = os.path.join(os.path.abspath(bundle_dir), "params")
        first_exc = None
        for i, candidate in enumerate(abstract_candidates):
            try:
                params = ckptr.restore(params_path, candidate)
                break
            except Exception as exc:  # orbax shape-validation mismatch
                # The FIRST candidate is the expected layout; if every
                # candidate fails, its error is the real cause (a
                # missing/corrupt checkpoint would otherwise surface as
                # the ALTERNATE candidate's confusing shape mismatch).
                if first_exc is None:
                    first_exc = exc
                if i == len(abstract_candidates) - 1:
                    raise first_exc
    finally:
        ckptr.close()
    if jax.process_count() > 1:
        # hand callers host numpy: device_put from a committed
        # single-device array to a global multi-process sharding is the
        # one transfer shape jax does not support
        params = jax.device_get(params)
    return model, params, meta
