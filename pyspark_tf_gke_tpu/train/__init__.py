from pyspark_tf_gke_tpu.train.losses import (
    mae_metric,
    mse_loss,
    softmax_cross_entropy,
    accuracy_metric,
)
from pyspark_tf_gke_tpu.train.state import TrainState
from pyspark_tf_gke_tpu.train.trainer import Trainer, TrainerTask
from pyspark_tf_gke_tpu.train.checkpoint import CheckpointManager

__all__ = [
    "mae_metric",
    "mse_loss",
    "softmax_cross_entropy",
    "accuracy_metric",
    "TrainState",
    "Trainer",
    "TrainerTask",
    "CheckpointManager",
]
