"""Serving deployment surface: HTTP (and stdin) serving of an exported
bundle.

The reference's terminal artifact had exactly one consumption path — a
human loads the saved Keras model and eyeballs predictions
(``workloads/raw-tf/test-model.py:13-56``). Here the terminal artifact
is a serving bundle (``train/export.py``), and this module closes the
loop from "directory on disk" to "deployed endpoint":

* ``BundleServer`` — loads a bundle (optionally tp-sharded over a mesh,
  optionally int8), serves

  - ``GET  /healthz``      → liveness/readiness (k8s probes),
  - ``POST /v1/generate``  → batch text completion,
  - ``POST /v1/score``     → per-text negative log-likelihood (the
    building block remote perplexity eval uses — evaluate/lm_eval.py
    ``--endpoint``);

* CLI: ``python -m pyspark_tf_gke_tpu.train.serve --bundle DIR
  [--port 8000] [--tp N] [--stdin]`` — the entry the k8s manifest
  (``infra/k8s/tpu/tpu-serve.yaml``) and the bastion launch script
  (``launch/serve_bundle.sh``) run.

Implementation notes (TPU-shaped, not an afterthought):

* Generation batches group prompts by token length — same-length
  prompts decode as ONE batched prefill+scan; each distinct
  (batch, prompt_len, max_new) shape hits the module-level jit cache in
  ``models/causal_lm.py``, so steady-state traffic compiles nothing.
* Scoring pads each batch up to a small set of bucket lengths
  (multiples of ``SCORE_BUCKET``) and masks the padding out of the NLL,
  so arbitrary-length texts reuse a handful of compiled shapes. Pads
  sit at the END of a causal sequence — they cannot influence the
  scored positions.
* One lock serializes device work; HTTP threads only parse/serialize.
  Single-program SPMD stays intact under a tp mesh.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pyspark_tf_gke_tpu.chaos.inject import chaos_fire
from pyspark_tf_gke_tpu.obs.events import get_event_log
from pyspark_tf_gke_tpu.obs.export import handle_obs_request
from pyspark_tf_gke_tpu.obs.metrics import get_registry, platform_families
from pyspark_tf_gke_tpu.obs.runtime import install_runtime_metrics
from pyspark_tf_gke_tpu.obs.stepstats import StepStatsRing
from pyspark_tf_gke_tpu.obs.trace import (
    TraceRecorder,
    annotate_request_shape,
    use_span,
)
from pyspark_tf_gke_tpu.parallel.distributed import as_host_array
from pyspark_tf_gke_tpu.utils.logging import get_logger

logger = get_logger("train.serve")

# Reject request bodies above this size with 413 before reading them —
# the handler otherwise trusts Content-Length and buffers the whole body.
MAX_BODY_BYTES = 8 << 20

SCORE_BUCKET = 64
MAX_BATCH = 64
SPEC_GAMMA = 4  # speculative draft chunk width (echoed in responses)


def _bucket(n: int, cap: int) -> int:
    return min(-(-n // SCORE_BUCKET) * SCORE_BUCKET, cap)


class RequestRejected(RuntimeError):
    """Load-shed / drain rejection BEFORE any device work: maps to HTTP
    429 (``queue_full``, ``tenant_quota``, ``tenant_queue_full``) or
    503 (``draining``) with a ``Retry-After`` header — overload
    degrades to fast rejection, not collapse. ``tenant`` is set on
    PER-TENANT sheds (quota / queue share): the handler surfaces it as
    the ``X-Tenant-Shed`` response header so the router knows the
    verdict is about one tenant, not replica health — no backoff, no
    re-route, no DOWN marking."""

    def __init__(self, reason: str, message: str, status: int,
                 retry_after_s: int = 1, tenant: Optional[str] = None):
        super().__init__(message)
        self.reason = reason
        self.status = int(status)
        self.retry_after_s = int(retry_after_s)
        self.tenant = tenant


def _draining_rejection() -> RequestRejected:
    """THE draining rejection — one definition for the front's
    admission gate, the whole-batch path, and the HTTP handler, so the
    status/message/Retry-After can never drift apart."""
    return RequestRejected(
        "draining",
        "server is draining (shutting down); retry against a live "
        "replica", status=503, retry_after_s=5)


def _reloading_rejection() -> RequestRejected:
    """Terminal handed to a request the bundle hot-swap could not drain
    within its grace window: explicit, retryable (the freshly swapped
    bundle serves the retry) — never a silent drop or a hang."""
    return RequestRejected(
        "reloading",
        "bundle hot-swap interrupted this request; retry", status=503,
        retry_after_s=1)


class ReloadInFlight(RuntimeError):
    """A bundle reload is already running (HTTP 409): reloads serialize
    — the coordinator retries after the in-flight one settles."""


class ProfileInFlight(RuntimeError):
    """A profiler capture is already running (HTTP 409): jax.profiler
    holds one process-global trace session — captures serialize, same
    contract as bundle reloads."""


class BundleReloadError(RuntimeError):
    """A reload failed (HTTP 502). ``rolled_back`` says whether the new
    bundle got as far as serving before the canary failed (True: the
    PREVIOUS generation was reinstalled and serves) or never installed
    at all (False: nothing changed). Either way the advertised
    ``bundle_generation`` did not advance."""

    def __init__(self, message: str, rolled_back: bool):
        super().__init__(message)
        self.rolled_back = bool(rolled_back)


class TokenBucket:
    """Refillable token-rate quota for ONE tenant: ``rate`` tokens/sec
    refill up to ``burst``. Admission charges the request's worst-case
    footprint (prompt + max_new_tokens) via :meth:`try_take`; the front
    refunds the UNUSED generation budget when the request delivers —
    so a quota shed can only ever happen at admission, never
    mid-stream (the charge already covers the whole generation).
    Thread-safe: handler threads take, the driver thread refunds."""

    def __init__(self, rate_per_s: float, burst: float):
        if rate_per_s <= 0:
            raise ValueError(f"rate must be > 0, got {rate_per_s}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._level = float(burst)  # start full: a fresh server must
        #   not 429 its first request
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        self._level = min(self.burst,
                          self._level + (now - self._last) * self.rate)
        self._last = now

    def try_take(self, n: float) -> bool:
        with self._lock:
            self._refill(time.monotonic())
            if self._level >= n:
                self._level -= n
                return True
            return False

    def refund(self, n: float) -> None:
        """Return unused charge (clamped to ``burst`` — a refund can
        never bank more than the bucket holds)."""
        if n <= 0:
            return
        with self._lock:
            self._refill(time.monotonic())
            self._level = min(self.burst, self._level + float(n))

    def retry_after_s(self, n: float) -> int:
        """Whole seconds until ``n`` tokens will be available at the
        refill rate — the per-tenant ``Retry-After`` a quota shed
        carries (computed from THIS tenant's own bucket, not a global
        constant)."""
        with self._lock:
            self._refill(time.monotonic())
            if self._level >= n:
                return 1
            need = min(float(n), self.burst) - self._level
        return max(1, int(-(-need // self.rate)))

    @property
    def level(self) -> float:
        with self._lock:
            self._refill(time.monotonic())
            return self._level


def parse_tenant_spec(spec) -> Optional[Dict[str, dict]]:
    """Parse the ``--tenants`` / ``SERVE_TENANTS`` spec into
    ``{tenant: {"weight": float, "rate": float|None, "burst": float}}``.

    Two forms:

    * JSON object — ``{"light": {"weight": 3},
      "noisy": {"weight": 1, "rate": 200, "burst": 400}}``;
    * compact — ``light=3,noisy=1:200:400`` i.e.
      ``name=weight[:rate[:burst]]``.

    ``weight`` drives the engine's DWRR admission share and the
    per-tenant slice of ``--max-queue-depth`` / ``--max-queued-tokens``.
    ``rate`` (tokens/sec, absent = unmetered) + ``burst`` (default
    2x rate) build the tenant's :class:`TokenBucket`. A ``"*"`` entry
    sets the defaults for tenants not named in the spec. Empty/None
    spec -> None (tenancy off: the pre-tenancy single-queue
    behavior)."""
    if not spec:
        return None
    if isinstance(spec, dict):
        raw = spec
    else:
        spec = str(spec).strip()
        if spec.startswith("{"):
            raw = json.loads(spec)
            if not isinstance(raw, dict):
                raise ValueError(f"tenant spec must be a JSON object, "
                                 f"got {type(raw).__name__}")
        else:
            raw = {}
            for part in spec.split(","):
                part = part.strip()
                if not part:
                    continue
                name, _, rest = part.partition("=")
                if not name or not rest:
                    raise ValueError(
                        f"bad tenant spec entry {part!r} (want "
                        "name=weight[:rate[:burst]])")
                fields = rest.split(":")
                entry: dict = {"weight": float(fields[0])}
                if len(fields) > 1 and fields[1]:
                    entry["rate"] = float(fields[1])
                if len(fields) > 2 and fields[2]:
                    entry["burst"] = float(fields[2])
                if len(fields) > 3:
                    raise ValueError(
                        f"bad tenant spec entry {part!r}: too many "
                        "fields")
                raw[name.strip()] = entry
    out: Dict[str, dict] = {}
    for name, entry in raw.items():
        if not isinstance(entry, dict):
            entry = {"weight": entry}  # {"light": 3} shorthand
        weight = float(entry.get("weight", 1.0))
        if weight <= 0:
            raise ValueError(
                f"tenant {name!r} weight must be > 0, got {weight}")
        rate = entry.get("rate")
        rate = float(rate) if rate is not None else None
        if rate is not None and rate <= 0:
            raise ValueError(
                f"tenant {name!r} rate must be > 0, got {rate}")
        burst = entry.get("burst")
        burst = (float(burst) if burst is not None
                 else (2.0 * rate if rate is not None else None))
        unknown = set(entry) - {"weight", "rate", "burst"}
        if unknown:
            raise ValueError(
                f"tenant {name!r}: unknown field(s) {sorted(unknown)}")
        out[str(name)] = {"weight": weight, "rate": rate, "burst": burst}
    if not out:
        return None
    return out


class DeadlineExceeded(RuntimeError):
    """The request's client-supplied deadline passed (HTTP 504): it was
    expired in queue or cancelled in-slot at a chunk boundary, so a
    dead client never holds a KV slot."""


class EngineShutdown(RuntimeError):
    """Terminal error delivered to every pending waiter when the front
    shuts down — a waiter must fail NOW, not at its wait() timeout."""


class EngineWedged(RuntimeError):
    """Terminal error the STEP WATCHDOG delivers to every in-flight
    waiter when an engine step exceeds ``--step-timeout`` (a hung or
    pathologically slow device dispatch): the client gets an explicit
    error terminal NOW instead of riding out its full request timeout
    against a wedged loop, and the engine rebuilds the moment the
    stuck step returns."""


class _ContinuousFront:
    """Thread front for the slot engine (train/continuous.py): ONE
    driver thread owns the device loop; HTTP handler threads submit
    token prompts and block on a per-request event. Requests admitted
    into KV slots as they free up — a long completion no longer stalls
    the short ones behind it (the whole-batch path's failure mode)."""

    def __init__(self, model, params, eos_id, num_slots: int,
                 chunk: int, mesh=None, announce: bool = False,
                 prefix_cache_size: int = 0, prefill_chunk: int = 0,
                 step_token_budget: int = 0,
                 pipeline_depth: int = 0, adaptive_chunk: bool = False,
                 schedule: str = "fifo", obs=None, event_log=None,
                 max_queue_depth: int = 0, max_queued_tokens: int = 0,
                 chaos=None, heartbeat=None, tenants=None,
                 step_timeout_s: float = 0.0, spec_tokens: int = 0,
                 draft_model=None, draft_params=None,
                 step_record_ring: int = 256, peak_flops: float = 0.0,
                 tracer=None):
        # multi-tenant fairness/quotas: parsed spec (parse_tenant_spec
        # output or an equivalent dict), or None = tenancy off (every
        # request rides the "default" tenant; admission bounds stay
        # GLOBAL, exactly the pre-tenancy behavior)
        self._tenants = parse_tenant_spec(tenants)
        self._tenant_weights = ({name: cfg["weight"]
                                 for name, cfg in self._tenants.items()}
                                if self._tenants else None)
        self._buckets: Dict[str, TokenBucket] = {}
        if self._tenants:
            for name, cfg in self._tenants.items():
                if cfg["rate"] is not None:
                    self._buckets[name] = TokenBucket(cfg["rate"],
                                                      cfg["burst"])
        # the FRONT owns the step-telemetry ring and threads it through
        # every engine it builds, so GET /stepz history and the /loadz
        # host-overhead fraction survive engine rebuilds
        self.stepstats = StepStatsRing(capacity=max(1,
                                                    int(step_record_ring)))
        self._engine_args = (model, params, eos_id, num_slots, chunk,
                             mesh, announce, prefix_cache_size,
                             prefill_chunk, step_token_budget,
                             pipeline_depth, adaptive_chunk,
                             schedule, self._tenant_weights,
                             spec_tokens, draft_model, draft_params,
                             self.stepstats, float(peak_flops))
        self._announce = announce
        self._obs = obs if obs is not None else platform_families()
        self._event_log = (event_log if event_log is not None
                           else get_event_log())
        # bounded admission: 0 = unbounded (the pre-hardening behavior);
        # past either bound submit() sheds with RequestRejected instead
        # of queueing work the server cannot finish in time
        self.max_queue_depth = int(max_queue_depth)
        self.max_queued_tokens = int(max_queued_tokens)
        # serve-side chaos (resilience.FaultInjector via --chaos): fires
        # inside the driver loop so the REAL rebuild path is exercised
        self._chaos = chaos
        self._chaos_step = 0
        # liveness signal from the driver loop itself — /healthz answers
        # from an HTTP thread even when the device loop is wedged, so
        # the k8s liveness probe watches THIS file's age instead
        self._heartbeat = heartbeat
        self.draining = threading.Event()
        self.engine = self._new_engine()
        self.lock = threading.Lock()
        self.new_work = threading.Event()
        self.stop = threading.Event()
        # rid -> [done_event, tokens|Exception|None, stream_q|None].
        # The DICT is guarded by its own lock (always inner to
        # self.lock): the step watchdog must reap waiters while the
        # driver thread is stuck inside engine.step() HOLDING
        # self.lock — a single lock would let one hung device dispatch
        # wedge the reaper too.
        self._results = {}
        self._results_lock = threading.Lock()
        self._warmed = []  # token lists, replayed into rebuilt engines
        # step watchdog (chaos-plane durability): when an engine step
        # runs longer than step_timeout_s (hung/failed device
        # dispatch), every in-flight waiter gets an explicit
        # EngineWedged error terminal and the engine rebuilds the
        # moment the step returns. 0 = off. _last_loop_ts is the
        # /livez liveness signal — it stalls exactly when the driver
        # loop does.
        self.step_timeout_s = float(step_timeout_s)
        self._step_started = None  # monotonic at engine.step() entry
        self._wedged = False
        self._last_loop_ts = time.monotonic()
        # on-demand profiler capture (POST /admin/profile): the driver
        # loop starts a jax.profiler trace at the next BUSY step and
        # stops it after N busy steps, emitting profile_trace_written
        # with the covered step-seq window + recent trace ids so an
        # xprof capture, a /stepz window and a /traces slow trace all
        # cross-link. One capture at a time (jax.profiler is
        # process-global) — a second request 409s.
        self._profile_lock = threading.Lock()
        self._profile = None
        self._tracer = tracer
        self.thread = threading.Thread(
            target=self._loop, name="continuous-engine", daemon=True)
        self.thread.start()
        # the watchdog thread ALWAYS runs (idle no-op sweeps at 1 Hz
        # while step_timeout_s <= 0) so the timeout really is a live
        # attribute: a front built with the watchdog off can arm it
        # at runtime and be reaped, not silently unprotected
        threading.Thread(target=self._watch_steps,
                         name="step-watchdog", daemon=True).start()

    def _new_engine(self):
        from pyspark_tf_gke_tpu.train.continuous import ContinuousEngine

        (model, params, eos_id, num_slots, chunk, mesh, announce,
         prefix_cache_size, prefill_chunk, step_token_budget,
         pipeline_depth, adaptive_chunk, schedule,
         tenant_weights, spec_tokens, draft_model,
         draft_params, stepstats, peak_flops) = self._engine_args
        return ContinuousEngine(model, params, num_slots=num_slots,
                                chunk=chunk, eos_token_id=eos_id,
                                mesh=mesh, announce=announce,
                                prefix_cache_size=prefix_cache_size,
                                prefill_chunk=prefill_chunk,
                                step_token_budget=step_token_budget,
                                pipeline_depth=pipeline_depth,
                                adaptive_chunk=adaptive_chunk,
                                schedule=schedule,
                                tenant_weights=tenant_weights,
                                spec_tokens=spec_tokens,
                                draft_model=draft_model,
                                draft_params=draft_params,
                                obs=self._obs,
                                stepstats=stepstats,
                                peak_flops=peak_flops)

    # -- tenancy helpers -------------------------------------------------

    def resolve_tenant(self, tenant: Optional[str]) -> str:
        """Normalize a CLIENT-SUPPLIED tenant id to the identity the
        fairness machinery runs on. No ``--tenants`` spec: always
        "default" — untrusted X-Tenant values must not be able to flip
        the engine out of its single-tenant FIFO/batch-admit fast path
        or mint unbounded metric label values on an unconfigured
        server. With a spec: ids named in it pass through; everything
        else folds into the ONE ``*`` aggregate — unlisted ids share a
        slice, a quota bucket and a label, so rotating fabricated
        tenant names gains an attacker nothing (no per-id queue share,
        no per-id state growth). Isolation is something you configure
        by naming the tenant."""
        if self._tenants is None:
            return "default"
        t = str(tenant) if tenant else "default"
        return t if (t in self._tenants and t != "*") else "*"

    def _tenant_share(self, tenant: str, bound: int) -> int:
        """This (resolved) tenant's weight-proportional slice of a
        global admission bound (``max_queue_depth`` /
        ``max_queued_tokens``). The denominator is the sum of ALL spec
        weights (an explicit ``*`` entry included); a spec without
        ``*`` gives the unlisted-tenant aggregate an implicit weight
        1.0 that widens only its OWN denominator — named tenants keep
        their natural shares, and every fabricated id shares the one
        aggregate slice, so shares sum to ~the bound regardless of how
        many ids a client invents."""
        cfgs = self._tenants
        total = sum(c["weight"] for c in cfgs.values())
        if tenant == "*" and "*" not in cfgs:
            w = 1.0
            total += w
        else:
            w = cfgs[tenant]["weight"]
        return max(1, int(bound * w / max(total, w)))

    def _bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        """The (resolved) tenant's quota bucket, or None (unmetered).
        One bucket per SPEC ENTRY only — unlisted tenants were already
        folded into ``*`` by :meth:`resolve_tenant`, so the bucket map
        is bounded by the spec and the refund path can never miss a
        bucket the charge path used."""
        if not self._tenants:
            return None
        return self._buckets.get(tenant)

    def _shed_tenant(self, tenant: str, reason: str, message: str,
                     retry_after_s: int) -> None:
        self._obs["serve_requests_rejected_total"].labels(
            reason=reason).inc()
        self._obs["serve_tenant_rejected_total"].labels(
            tenant=tenant, reason=reason).inc()
        raise RequestRejected(reason, message, status=429,
                              retry_after_s=retry_after_s, tenant=tenant)

    def charge_tokens(self, tenant: Optional[str], n: int) -> str:
        """Charge ``n`` tokens of NON-ENGINE device work (the
        whole-batch /v1/score path) against the tenant's quota bucket.
        Exact work, charged up front, no refund. Returns the resolved
        tenant; raises the same per-tenant 429 / terminal-400 taxonomy
        as admission — a tenant throttled on generate must not
        saturate the device unmetered through score."""
        tenant = self.resolve_tenant(tenant)
        bucket = self._bucket_for(tenant)
        if bucket is None:
            return tenant
        if n > bucket.burst:
            raise ValueError(
                f"score batch of {n} tokens exceeds tenant {tenant!r} "
                f"quota burst {bucket.burst:g} — split the batch")
        if not bucket.try_take(n):
            self._shed_tenant(
                tenant, "tenant_quota",
                f"tenant {tenant!r} token quota exhausted (score "
                f"batch needs {n} tokens; refill {bucket.rate:g}/s)",
                retry_after_s=bucket.retry_after_s(n))
        return tenant

    def _settle(self, req) -> None:
        """One engine-delivered request's quota reconciliation: refund
        the UNUSED generation budget to its tenant's bucket (charged as
        prompt + max_new_tokens at admission, so a deadline expiry or
        early eos returns the difference) and count delivered tokens.
        Runs on the driver thread, once per delivery."""
        bucket = self._buckets.get(req.tenant)
        if bucket is not None:
            unused = int(req.max_new_tokens) - len(req.tokens)
            if unused > 0:
                bucket.refund(unused)
        if req.tokens:
            self._obs["serve_tenant_tokens_total"].labels(
                tenant=req.tenant).inc(len(req.tokens))

    def _check_admission(self, prompt_len: int, max_new_tokens: int,
                         tenant: str = "default") -> None:
        """Bounded admission + drain gate (caller holds ``self.lock``).
        Raises :class:`RequestRejected` — BEFORE the engine sees the
        request, so shedding costs no device work and no KV pages.

        Shed ordering: drain first (503 — replica lifecycle beats
        everything), then the terminal footprint check (400), then —
        with a ``--tenants`` spec — the PER-TENANT gates: queue share
        (this tenant's weight-proportional slice of the global bounds)
        and token-rate quota, each a 429 carrying the tenant and a
        Retry-After computed from that tenant's own state. Without a
        spec the global bounds apply verbatim (pre-tenancy behavior).
        A tenant over its share/quota sheds while every other tenant
        keeps admitting — the global queue never rejects a tenant that
        is inside its own share."""
        if self.draining.is_set():
            self._obs["serve_requests_rejected_total"].labels(
                reason="draining").inc()
            raise _draining_rejection()
        ask = int(prompt_len) + int(max_new_tokens)
        if self.max_queued_tokens and ask > self.max_queued_tokens:
            # the request ALONE busts the budget: no amount of
            # retrying can ever clear that — terminal 400 (caller
            # error), not a 429 retry-forever loop
            raise ValueError(
                f"request footprint {ask} tokens (prompt + budget) "
                f"exceeds max_queued_tokens {self.max_queued_tokens}")
        if self._tenants is None:
            if self.max_queue_depth:
                depth = self.engine.queue_depth()
                if depth >= self.max_queue_depth:
                    self._obs["serve_requests_rejected_total"].labels(
                        reason="queue_full").inc()
                    raise RequestRejected(
                        "queue_full",
                        f"admission queue full ({depth} waiting >= "
                        f"max_queue_depth {self.max_queue_depth})",
                        status=429, retry_after_s=1)
            if self.max_queued_tokens:
                queued = self.engine.queued_tokens()
                if queued + ask > self.max_queued_tokens:
                    self._obs["serve_requests_rejected_total"].labels(
                        reason="queue_full").inc()
                    raise RequestRejected(
                        "queue_full",
                        f"queued-token budget exhausted ({queued} queued "
                        f"+ {ask} requested > max_queued_tokens "
                        f"{self.max_queued_tokens})",
                        status=429, retry_after_s=1)
            return
        # -- per-tenant gates (tenancy configured) -----------------------
        if self.max_queue_depth:
            share = self._tenant_share(tenant, self.max_queue_depth)
            depth = self.engine.queue_depth(tenant)
            if depth >= share:
                self._shed_tenant(
                    tenant, "tenant_queue_full",
                    f"tenant {tenant!r} admission-queue share full "
                    f"({depth} waiting >= share {share} of "
                    f"max_queue_depth {self.max_queue_depth})",
                    retry_after_s=1)
        if self.max_queued_tokens:
            share = self._tenant_share(tenant, self.max_queued_tokens)
            if ask > share:
                raise ValueError(
                    f"request footprint {ask} tokens exceeds tenant "
                    f"{tenant!r} queued-token share {share}")
            queued = self.engine.queued_tokens(tenant)
            if queued + ask > share:
                self._shed_tenant(
                    tenant, "tenant_queue_full",
                    f"tenant {tenant!r} queued-token share exhausted "
                    f"({queued} queued + {ask} requested > share "
                    f"{share} of max_queued_tokens "
                    f"{self.max_queued_tokens})",
                    retry_after_s=1)
        bucket = self._bucket_for(tenant)
        if bucket is not None:
            if ask > bucket.burst:
                raise ValueError(
                    f"request footprint {ask} tokens exceeds tenant "
                    f"{tenant!r} quota burst {bucket.burst:g} — it can "
                    "never admit at any retry")
            if not bucket.try_take(ask):
                # Retry-After from THIS tenant's refill rate: the shed
                # is a quota verdict about the tenant, and the header
                # tells it exactly when its own bucket will cover the
                # request — other tenants' admission is untouched
                self._shed_tenant(
                    tenant, "tenant_quota",
                    f"tenant {tenant!r} token quota exhausted "
                    f"(request needs {ask} tokens; refill "
                    f"{bucket.rate:g}/s)",
                    retry_after_s=bucket.retry_after_s(ask))

    def submit(self, prompt_ids, max_new_tokens: int,
               temperature: float = 0.0, top_p=None,
               seed: int = 0, deadline_s=None,
               tenant: str = "default", span=None) -> int:
        """Queue a request (non-blocking); pair with ``wait``.
        ``deadline_s``: seconds from now the client still cares about
        the answer — past it the engine expires the request at the next
        chunk boundary and ``wait`` raises :class:`DeadlineExceeded`.
        ``tenant``: fairness/quota identity (header/body-extracted by
        the HTTP layer; "default" when absent) — normalized here, so
        unlisted ids fold into the ``*`` aggregate and a no-spec
        server never sees anything but "default". ``span``: the
        request's trace span (obs/trace.py) — the engine annotates its
        queue/admission/prefill/token timeline onto it."""
        tenant = self.resolve_tenant(tenant)
        # shape BEFORE the admission gates: a shed request is demand
        # the replay/capacity plane must still see on its trace
        annotate_request_shape(span, tenant=tenant,
                               prompt_tokens=len(prompt_ids),
                               max_new_tokens=max_new_tokens,
                               deadline_s=deadline_s)
        done = threading.Event()
        with self.lock:
            self._check_admission(len(prompt_ids), max_new_tokens,
                                  tenant=tenant)
            try:
                rid = self.engine.submit(prompt_ids, max_new_tokens,
                                         temperature=temperature,
                                         top_p=top_p, seed=seed,
                                         deadline_s=deadline_s,
                                         tenant=tenant, span=span)
            except BaseException:
                # the quota charge landed in _check_admission; a failed
                # engine submit must hand it back or the tenant pays
                # for a request that never queued
                bucket = self._buckets.get(tenant)
                if bucket is not None:
                    bucket.refund(len(prompt_ids) + int(max_new_tokens))
                raise
            with self._results_lock:
                self._results[rid] = [done, None, None]
        self._obs["serve_tenant_requests_total"].labels(
            tenant=tenant).inc()
        self.new_work.set()
        return rid

    def wait(self, rid: int, timeout_s: float = 600.0):
        with self._results_lock:
            entry = self._results.get(rid)
        if entry is None:
            raise KeyError(f"unknown or already-collected request {rid}")
        done = entry[0]
        if not done.wait(timeout_s):
            with self.lock:
                # free the KV slot too — an abandoned request must not
                # keep decoding tokens nobody will read (overload would
                # otherwise starve the very queue that caused the
                # timeout)
                self.engine.cancel(rid)
                with self._results_lock:
                    self._results.pop(rid, None)
            raise RuntimeError(
                f"continuous decode timed out after {timeout_s}s")
        with self._results_lock:
            # pop-if-present: the step watchdog removes reaped entries
            # itself — the captured entry's result slot was written
            # BEFORE its event was set either way
            self._results.pop(rid, None)
        result = entry[1]
        if isinstance(result, (DeadlineExceeded, EngineShutdown,
                               RequestRejected)):
            # typed: the handler maps these to 504 / 500 / the shed's
            # own status (a hot-swap 'reloading' terminal is a 503)
            raise result
        if isinstance(result, Exception):
            raise RuntimeError(
                f"continuous engine failed this request: {result}")
        return result

    def submit_and_wait(self, prompt_ids, max_new_tokens: int,
                        timeout_s: float = 600.0):
        return self.wait(self.submit(prompt_ids, max_new_tokens),
                         timeout_s)

    def warm_prefix(self, prefix_ids) -> int:
        """Prefill + cache a shared prompt prefix (serialized with the
        driver loop's device work). The token list is retained so an
        engine rebuild after a failed step re-warms automatically —
        deploy-time warms must not silently vanish on a transient
        device error."""
        with self.lock:
            n = self.engine.warm_prefix(prefix_ids)
            toks = [int(t) for t in prefix_ids]
            if toks not in self._warmed:
                self._warmed.append(toks)
                cap = self.engine.warm_capacity  # dense LRU entries,
                #   or the radix cache's fixed re-warm horizon
                del self._warmed[:-cap]
            return n

    def export_prefix_pages(self, prefix_ids):
        """Read the radix-cached KV pages covering ``prefix_ids`` back
        to the host (serialized with the driver loop's device work) —
        the prefill replica's half of a disaggregated handoff."""
        with self.lock:
            return self.engine.export_prefix_pages(prefix_ids)

    def import_prefix_pages(self, token_ids, layers) -> int:
        """Install transferred KV pages + adopt them into the radix
        trie (serialized with the driver loop's device work) — the
        decode replica's half of a disaggregated handoff."""
        with self.lock:
            return self.engine.import_prefix_pages(token_ids, layers)

    def abandon(self, rid: int) -> None:
        """Give up on a submitted request: free its KV slot / queue spot
        and drop its results entry (idempotent). BOUNDED acquire on the
        front lock: during a wedged step the driver holds it for the
        whole hang, and abandon is exactly the cleanup path the
        watchdog's bounded-latency promise routes through — when the
        lock can't be had promptly, skip the engine-side cancel (the
        rebuild that follows the wedge clears engine state anyway; on
        a merely-busy engine the request runs out its budget and its
        delivery finds no waiter) and still drop the waiter entry."""
        acquired = self.lock.acquire(timeout=1.0)
        try:
            if acquired:
                self.engine.cancel(rid)
        finally:
            if acquired:
                self.lock.release()
        with self._results_lock:
            self._results.pop(rid, None)

    def submit_internal(self, prompt_ids, max_new_tokens: int) -> int:
        """Engine submit that BYPASSES the admission/quota/drain gates —
        for server-internal probes only (the bundle hot-swap canary): a
        canary shed by overload or a drained tenant bucket would roll
        back a perfectly good bundle exactly when the fleet is busiest.
        The reserved tenant name keeps it out of every client bucket
        (no charge, so no refund at delivery either)."""
        done = threading.Event()
        with self.lock:
            rid = self.engine.submit(prompt_ids, max_new_tokens,
                                     tenant="__internal__")
            with self._results_lock:
                self._results[rid] = [done, None, None]
        self.new_work.set()
        return rid

    def submit_stream(self, prompt_ids, max_new_tokens: int,
                      deadline_s=None, tenant: str = "default",
                      span=None):
        """Streaming variant: returns (rid, queue). The queue receives
        token-id lists as they decode, then a terminal item — [] on
        completion, an Exception on engine failure / deadline expiry /
        shutdown. The consumer must drain it (bounded: max_new_tokens
        items + terminal). Quota note: the tenant charge covers the
        FULL budget at admission, so a stream can never be
        quota-killed mid-flight — the unused remainder refunds at the
        terminal delivery."""
        import queue as _queue

        tenant = self.resolve_tenant(tenant)
        annotate_request_shape(span, tenant=tenant,
                               prompt_tokens=len(prompt_ids),
                               max_new_tokens=max_new_tokens,
                               deadline_s=deadline_s)
        q = _queue.Queue()
        done = threading.Event()
        with self.lock:
            self._check_admission(len(prompt_ids), max_new_tokens,
                                  tenant=tenant)
            try:
                rid = self.engine.submit(prompt_ids, max_new_tokens,
                                         on_tokens=q.put,
                                         deadline_s=deadline_s,
                                         tenant=tenant, span=span)
            except BaseException:
                bucket = self._buckets.get(tenant)
                if bucket is not None:
                    bucket.refund(len(prompt_ids) + int(max_new_tokens))
                raise
            with self._results_lock:
                self._results[rid] = [done, None, q]  # same shape as
                #                                       submit
        self._obs["serve_tenant_requests_total"].labels(
            tenant=tenant).inc()
        self.new_work.set()
        return rid, q

    def _deliver_finished(self, finished) -> None:
        """Deliver one settled step's finished requests to their
        waiters: quota refund + per-tenant token accounting for every
        delivery (completion AND expiry — a deadline-expired request
        hands its unused generation budget back to its tenant's
        bucket), then the result/terminal. Caller holds ``self.lock``
        (the driver loop and the hot-swap drain both run it).

        The results lock is taken ONCE per settled step, not once per
        request: on the pipelined engine delivery is the host work
        that must fit inside the in-flight chunk's compute, and N
        lock round-trips per step (vs the submit path and the
        watchdog) were measurable on the 1-vCPU box. Per-token waiter
        wakeups are unaffected — token streaming rides the engine's
        ``on_tokens`` queues; this path only writes terminals."""
        if not finished:
            return
        for req in finished:
            # quota settlement needs no waiter state — keep it outside
            # the results lock
            self._settle(req)
        # (the terminal span event is emitted by the ENGINE at the
        # state transition itself — one emitter for served and
        # direct callers alike; the HTTP layer still stamps the
        # status code it maps the outcome to)
        with self._results_lock:
            for req in finished:
                # delivery happens UNDER the lock, and only if nobody
                # delivered first: a step returning right at the
                # watchdog timeout races the reaper, and a waiter must
                # get exactly ONE terminal — whichever side claims the
                # still-empty slot inside the lock wins, the other
                # skips (the reaper also removes entries, so the get
                # below usually misses outright)
                slot = self._results.get(req.rid)
                if slot is None or slot[1] is not None \
                        or slot[0].is_set():
                    continue
                if req.expired:
                    err = DeadlineExceeded(
                        f"request deadline exceeded after "
                        f"{len(req.tokens)} decoded token(s)")
                    slot[1] = err
                    slot[0].set()
                    if slot[2] is not None:
                        slot[2].put(err)
                    continue
                slot[1] = req.tokens
                slot[0].set()
                if slot[2] is not None:  # streaming terminal
                    slot[2].put([])

    def swap_model(self, model, params, eos_id, drain_s: float = 30.0):
        """Bundle hot-swap: replace the engine's model/params/eos.

        Holds the front lock end to end, so HTTP submits (and the
        driver loop) WAIT rather than race the swap. The OLD engine is
        stepped to completion right here — in-flight requests and open
        streams keep delivering tokens and finish on the weights they
        started on — bounded by ``drain_s``; anything still unfinished
        past the bound gets an explicit retryable 'reloading' terminal
        (503 + Retry-After), the same contract as every other shed:
        zero hangs, zero silent drops. The NEW engine then starts
        empty; warmed prefixes are dropped (they were tokenized and
        prefilled under the old bundle)."""
        with self.lock:
            args = list(self._engine_args)
            args[0], args[1], args[2] = model, params, eos_id
            self._engine_args = tuple(args)
            deadline = time.monotonic() + float(drain_s)
            try:
                while time.monotonic() < deadline:
                    if not self.engine.busy:
                        break
                    self._deliver_finished(self.engine.step())
                # quiesce the pipeline even when the drain deadline
                # cut the loop short: settle every in-flight chunk
                # (bounded — at most pipeline_depth collects) so no
                # speculative chunk is abandoned mid-flight with its
                # tokens undelivered and its page refs held when the
                # engine below is replaced
                self._deliver_finished(self.engine.quiesce())
            except Exception:  # noqa: BLE001 — drain is best-effort;
                # the explicit-terminal sweep below covers the leftovers
                logger.exception(
                    "old engine failed while draining for a bundle swap")
            try:
                # accepted-but-undelivered requests: terminal span
                # verdict (a reload past its drain bound is a SHED) +
                # refund their quota charges before the old engine is
                # dropped
                for req in self.engine.fail_outstanding("shed"):
                    self._settle(req)
            except Exception:  # noqa: BLE001 — refunds must not block
                pass           # the swap
            err = _reloading_rejection()
            with self._results_lock:
                # claim-and-write under the lock (same exactly-one-
                # terminal discipline as _deliver_finished: the step
                # watchdog may race this sweep)
                for slot in self._results.values():
                    if slot[1] is None and not slot[0].is_set():
                        self._obs["serve_requests_rejected_total"].labels(
                            reason="reloading").inc()
                        slot[1] = err
                        slot[0].set()
                        if slot[2] is not None:
                            slot[2].put(err)
            self.engine = self._new_engine()
            self._warmed.clear()

    def _watch_steps(self):
        """Watchdog thread: reap waiters stuck behind a hung engine
        step. Touches ONLY ``_results_lock`` — the driver holds
        ``self.lock`` for the whole stuck step, so the reaper must
        never want it."""
        while not self.stop.is_set():
            timeout = self.step_timeout_s
            started = self._step_started
            if (timeout > 0 and started is not None
                    and time.monotonic() - started > timeout):
                self._reap_wedged(time.monotonic() - started)
            # poll re-derived each sweep: the timeout is a plain
            # attribute so operators/tests may retune it live (e.g.
            # generous through warmup compiles, tight at steady state;
            # 0 = disarmed — the thread idles at 1 Hz)
            self.stop.wait(max(0.05, min(1.0, timeout / 4))
                           if timeout > 0 else 1.0)

    def _reap_wedged(self, stuck_s: float) -> None:
        """One watchdog intervention: flag the wedge (the driver loop
        rebuilds the engine when the stuck step returns; /livez
        reports it meanwhile) and fail every pending waiter with an
        explicit EngineWedged error terminal — exactly one terminal
        per request, delivered NOW, instead of a silent hang into each
        client's own timeout. Re-fires each poll while the step stays
        stuck, so waiters that were mid-submit when the wedge began
        are caught on the next sweep."""
        first = not self._wedged
        self._wedged = True
        err = EngineWedged(
            f"engine step exceeded step_timeout {self.step_timeout_s:g}s "
            f"(stuck {stuck_s:.1f}s); the step watchdog failed this "
            "request")
        reaped = 0
        with self._results_lock:
            # entries stay in the table (wait() pops them and surfaces
            # the TYPED EngineWedged — deleting here made a rid reaped
            # between submit() and wait() raise a generic KeyError);
            # the slot[1]-is-None claim prevents re-reaping, and the
            # delivery path's own claim check prevents a returning
            # step from double-terminating a reaped waiter
            for slot in self._results.values():
                if slot[1] is None and not slot[0].is_set():
                    slot[1] = err
                    slot[0].set()
                    if slot[2] is not None:
                        slot[2].put(err)
                    reaped += 1
        if first or reaped:
            self._obs["serve_step_watchdog_reaps_total"].inc()
            self._event_log.emit("engine_watchdog_reap", reaped=reaped,
                                 stuck_s=round(stuck_s, 3),
                                 step_timeout_s=self.step_timeout_s)
            logger.error(
                "step watchdog: engine step stuck %.1fs (> %gs); "
                "failed %d in-flight request(s); engine rebuilds when "
                "the step returns", stuck_s, self.step_timeout_s, reaped)

    def start_profile(self, output_dir: str, steps: int) -> dict:
        """Arm an on-demand ``jax.profiler`` capture: the driver loop
        starts the trace at the next BUSY step and stops it after
        ``steps`` busy steps, emitting ``profile_trace_written``.
        Raises :class:`ProfileInFlight` while one is armed/running
        (HTTP 409 — jax.profiler holds one process-global session).
        The capture waits for real traffic: an idle engine holds the
        armed capture until work arrives."""
        steps = int(steps)
        if steps < 1:
            raise ValueError(f"profile steps must be >= 1, got {steps}")
        with self._profile_lock:
            if self._profile is not None:
                raise ProfileInFlight(
                    "a profiler capture is already in flight")
            self._profile = {"dir": str(output_dir), "steps": steps,
                             "remaining": steps, "started": False,
                             "seq_first": None, "seq_last": None}
        return {"output_dir": str(output_dir), "steps": steps,
                "armed": True}

    def profile_in_flight(self) -> bool:
        with self._profile_lock:
            return self._profile is not None

    def _profile_maybe_start(self) -> None:
        """Driver-loop hook, just before a busy step: start the armed
        capture (once)."""
        p = self._profile
        if p is None or p["started"]:
            return
        try:
            jax.profiler.start_trace(p["dir"])
            p["started"] = True
            logger.info("profiler capture started -> %s (%d steps)",
                        p["dir"], p["steps"])
        except Exception:  # noqa: BLE001 — a broken profiler session
            # must not take the driver loop down; disarm and report
            logger.exception("jax.profiler.start_trace failed; "
                             "capture disarmed")
            with self._profile_lock:
                self._profile = None

    def _profile_note_step(self, seq: int) -> None:
        """Driver-loop hook, after a step that CLOSED a record (no-op
        spins don't advance a capture): count it and stop the capture
        at zero, stamping the covered step-seq window and the
        recorder's recent trace ids into the event — the cross-links
        that let an xprof capture, a /stepz window and a /traces slow
        trace name each other. ``seq`` is the just-closed record's
        seq: first/last counted seqs bound the window, so both name
        records that actually entered the ring (a discarded no-op
        step's consumed seq never appears)."""
        p = self._profile
        if p is None or not p["started"]:
            return
        if p["seq_first"] is None:
            p["seq_first"] = seq
        p["seq_last"] = seq
        p["remaining"] -= 1
        if p["remaining"] > 0:
            return
        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            logger.exception("jax.profiler.stop_trace failed")
        trace_ids = []
        if self._tracer is not None:
            try:
                trace_ids = [t.get("trace_id")
                             for t in self._tracer.traces(limit=8)]
            except Exception:  # noqa: BLE001 — best-effort cross-link
                pass
        self._event_log.emit(
            "profile_trace_written", output_dir=p["dir"],
            steps=p["steps"], step_seq_first=p["seq_first"],
            step_seq_last=p["seq_last"], trace_ids=trace_ids)
        logger.info("profiler capture written to %s (steps %s..%s)",
                    p["dir"], p["seq_first"], p["seq_last"])
        with self._profile_lock:
            self._profile = None

    def _loop(self):
        beat = 0
        while not self.stop.is_set():
            beat += 1
            self._last_loop_ts = time.monotonic()  # /livez signal
            if self._heartbeat is not None:
                try:
                    self._heartbeat.beat(beat)
                except OSError:  # liveness signal must never take the
                    pass         # driver loop down with it
            busy = False
            seq0 = None  # first seq this iteration's step could close
            with self.lock:
                try:
                    busy = self.engine.busy
                    if busy and self._chaos is not None:
                        # counted on BUSY iterations only (deterministic
                        # against idle-spin timing); a raise here lands
                        # in the rebuild handler below — the exact path
                        # a real failed device step takes
                        self._chaos_step += 1
                        self._chaos.maybe_slow(self._chaos_step)
                        self._chaos.maybe_fail(self._chaos_step)
                    if busy:
                        self._profile_maybe_start()
                        seq0 = self.engine.stepstats.next_seq
                        self._step_started = time.monotonic()
                    try:
                        finished = self.engine.step() if busy else []
                    finally:
                        self._step_started = None
                    t_deliver = time.monotonic()
                    self._deliver_finished(finished)
                    if busy:
                        # retire sweep after delivery: the in-flight
                        # chunk often goes ready while the host
                        # delivers — observe it here so the delivery
                        # time stays out of its device-busy interval
                        self.engine.poll_retire()
                        # the one step phase that runs OUTSIDE
                        # engine.step(): amend delivery time onto the
                        # just-closed record (wall grows with it, so
                        # the phase-sum invariant holds). seq-guarded:
                        # a step that discarded its record (nothing to
                        # do) must not smear delivery onto an OLD one.
                        rec = self.engine.stepstats.last_record
                        if (rec is not None and rec.closed
                                and rec.seq >= seq0):
                            self.engine.stepstats.add_deliver(
                                rec, (time.monotonic() - t_deliver)
                                * 1000.0)
                            if self._wedged:
                                # the watchdog reaped this step's
                                # waiters while it hung: relabel the
                                # record (amend-in-place — it was
                                # closed exactly once above)
                                self.engine.stepstats.mark_reaped(rec)
                            # capture progress counts CLOSED step
                            # records only: a busy iteration whose
                            # step discarded its record (blocked
                            # admission no-op spin) must not complete
                            # the profile over zero device work — the
                            # emitted step-seq window has to name
                            # records that exist
                            self._profile_note_step(rec.seq)
                    if self._wedged:
                        # the stuck step RETURNED: its waiters were
                        # already reaped (completions among `finished`
                        # settled above; their waiter entries are gone
                        # so nothing double-delivers) — the engine
                        # state is untrustworthy, rebuild through the
                        # one failed-step path below
                        self._wedged = False
                        raise RuntimeError(
                            "engine step exceeded the watchdog timeout; "
                            "rebuilding")
                except Exception as exc:  # noqa: BLE001 — driver thread
                    # One failed step must not brick serving: the engine
                    # state may be mid-chunk garbage, so fail every
                    # in-flight request LOUDLY and rebuild the engine —
                    # later requests get a fresh slot pool.
                    logger.exception(
                        "continuous engine step failed; failing %d "
                        "in-flight request(s) and rebuilding the engine",
                        len(self._results))
                    self._obs["serve_engine_rebuilds_total"].inc()
                    self._event_log.emit(
                        "engine_rebuilt", inflight=len(self._results),
                        error=f"{type(exc).__name__}: {exc}"[:500])
                    # a failed step still closed a record (outcome=
                    # error) into the ring: advance any armed capture
                    # or a persistently failing engine would leave the
                    # process-global jax trace open forever (every
                    # later /admin/profile 409s with no disarm path)
                    rec = self.engine.stepstats.last_record
                    if (seq0 is not None and rec is not None
                            and rec.closed and rec.seq >= seq0):
                        self._profile_note_step(rec.seq)
                    try:
                        # the dead engine's accepted-but-undelivered
                        # requests never reach step()'s delivery path:
                        # mark them terminally failed (exactly one
                        # terminal span verdict each) and settle them
                        # HERE or their quota charges leak and the
                        # tenant pays 429s for work that was never done
                        for req in self.engine.fail_outstanding("error"):
                            self._settle(req)
                    except Exception:  # noqa: BLE001 — refunds must
                        pass           # not block the rebuild
                    with self._results_lock:
                        for slot in self._results.values():
                            if slot[1] is None:
                                slot[1] = exc
                                slot[0].set()
                                if slot[2] is not None:
                                    slot[2].put(exc)
                    if self._announce:
                        # workers must restart from zeros WITH us: their
                        # replica may hold the half-mutated state of the
                        # op that just failed
                        from pyspark_tf_gke_tpu.train import serving

                        with serving.mh_lock():
                            serving.announce_cb_reset()
                    self.engine = self._new_engine()
                    for toks in self._warmed:
                        try:
                            self.engine.warm_prefix(toks)
                        except Exception:  # noqa: BLE001
                            logger.exception(
                                "re-warm of a cached prefix failed "
                                "after engine rebuild")
                    busy = False
            if not busy:
                # idle: park until a submit wakes us (short timeout so
                # shutdown stays prompt)
                self.new_work.wait(0.05)
                self.new_work.clear()

    def begin_drain(self) -> None:
        """Stop admission: every later submit is rejected 503. Requests
        already queued or in slots keep decoding to completion."""
        self.draining.set()
        self._obs["serve_draining"].set(1)

    def drain(self, timeout_s: float) -> bool:
        """Block until every accepted request has delivered its result
        (completion, deadline expiry, or error) and the engine is idle,
        or ``timeout_s`` elapses. Returns True when fully drained.
        Call :meth:`begin_drain` first or new work keeps arriving."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self.lock:
                busy = self.engine.busy
                with self._results_lock:
                    pending = any(
                        slot[1] is None and not slot[0].is_set()
                        for slot in self._results.values())
            if not pending and not busy:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    def shutdown(self):
        self.stop.set()
        self.new_work.set()
        self.thread.join(timeout=10)
        with self._profile_lock:
            p, self._profile = self._profile, None
        if p is not None and p.get("started"):
            try:  # don't leave a process-global trace session dangling
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
        # Fail every still-pending waiter NOW with a terminal shutdown
        # error — before this, a waiter blocked in wait() sat out its
        # FULL timeout (600s default) against a driver thread that was
        # already gone, and a streaming consumer hung on its queue.
        err = EngineShutdown(
            "serving front shut down while the request was in flight")
        with self.lock:
            with self._results_lock:
                for slot in self._results.values():
                    if slot[1] is None and not slot[0].is_set():
                        slot[1] = err
                        slot[0].set()
                        if slot[2] is not None:
                            slot[2].put(err)


class BundleServer:
    """Loads a serving bundle and answers generate/score requests.

    ``mesh`` (optional): a tp mesh — params are placed with
    ``shard_params_for_serving`` and every call runs under the mesh
    context (XLA inserts the collectives)."""

    def __init__(self, bundle_dir: str, mesh=None, int8_kv: bool = False,
                 draft_bundle_dir: str = "", continuous_slots: int = 0,
                 continuous_chunk: int = 8, prefix_cache_size: int = 0,
                 prefill_chunk: int = 0, step_token_budget: int = 0,
                 continuous_pipeline: int = 1,
                 adaptive_chunk: bool = False, schedule: str = "fifo",
                 registry=None, event_log=None,
                 max_queue_depth: int = 0, max_queued_tokens: int = 0,
                 chaos_spec: str = "", heartbeat_file: str = "",
                 tenants_spec: str = "", admin_token: str = "",
                 trace_sample: float = 0.01,
                 trace_slow_ms: float = 1000.0,
                 step_timeout_s: float = 0.0,
                 live_stall_s: float = 120.0,
                 spec_tokens: int = 0,
                 step_record_ring: int = 256,
                 peak_flops: float = 0.0,
                 role: str = "mixed"):
        from pyspark_tf_gke_tpu.train.resilience import retry_with_backoff

        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"role must be mixed, prefill or decode, got {role!r}")
        # disaggregated serving role, advertised on /loadz: the router
        # sends long-prompt admissions to `prefill` replicas and keeps
        # ordinary generate traffic on `decode`/`mixed` ones. ADVISORY
        # — every role still serves every endpoint, so a degraded
        # fleet (all prefill replicas down) falls back to the normal
        # path instead of erroring.
        self.role = role
        self.mesh = mesh
        self._int8_kv = bool(int8_kv)
        self.draft_model = self.draft_params = None
        self.draft_bundle_dir = draft_bundle_dir
        self.model, self.params, self.meta, self.tokenizer = (
            self._load_and_verify(bundle_dir))
        if draft_bundle_dir:
            # speculative decoding: single-prompt greedy requests verify
            # a cheap draft's proposals in chunk forwards — same tokens,
            # fewer target steps (models/speculative.py)
            _permanent = (FileNotFoundError, ValueError, KeyError,
                          TypeError)
            from pyspark_tf_gke_tpu.train.export import (
                load_serving_bundle,
            )

            self.draft_model, self.draft_params, _ = retry_with_backoff(
                lambda: load_serving_bundle(draft_bundle_dir),
                op="bundle_load", give_up_on=_permanent)
            if (self.draft_model.cfg.vocab_size
                    != self.model.cfg.vocab_size):
                raise ValueError(
                    f"draft bundle vocab {self.draft_model.cfg.vocab_size} "
                    f"!= target vocab {self.model.cfg.vocab_size}")
            if mesh is not None:
                from pyspark_tf_gke_tpu.train.serving import (
                    shard_params_for_serving,
                )

                # the draft rides the same mesh — unsharded draft arrays
                # would forfeit its tp memory/latency win and break on
                # multi-host meshes
                self.draft_params = shard_params_for_serving(
                    self.draft_model, self.draft_params, mesh)
        self.bundle_dir = bundle_dir
        # bundle hot-swap (the pipeline plane's publish path): one
        # reload at a time; the generation only advances after a
        # successful swap + canary, and rides /healthz + /loadz so the
        # coordinator (and the router's prober) can confirm a rollout
        self.admin_token = admin_token
        self._reload_lock = threading.Lock()
        self.bundle_generation = int(
            self.meta.get("pipeline_generation", 1))
        self.multi_host = jax.process_count() > 1
        if self.multi_host and mesh is None:
            raise ValueError("multi-host serving needs a mesh spanning "
                             "all processes (set --tp / SERVE_TP)")
        self._lock = threading.Lock()  # one model, one device queue
        # Operational metrics live on the SHARED obs registry (obs/):
        # one /metrics scrape correlates serve counters with the train
        # plane (same-process trainers) and the runtime collectors —
        # what the reference world's kubectl-top/metrics-server loop
        # becomes when the server itself is first-party. The legacy
        # pyspark_tf_gke_tpu_serve_* exposition names stay as aliases
        # (metrics_text) so serve_bundle.sh-era scrape configs keep
        # working.
        self.registry = registry if registry is not None else get_registry()
        self._obs = platform_families(self.registry)
        install_runtime_metrics(self.registry)
        self._obs["serve_bundle_generation"].set(self.bundle_generation)
        self.event_log = (event_log if event_log is not None
                          else get_event_log())
        # request tracing (obs/trace.py): every HTTP request gets a
        # span that adopts the client's traceparent (or mints a root);
        # the engine annotates the request's queue/admission/prefill/
        # token timeline onto it, GET /traces serves the retained ring.
        # sample 0 + slow 0 short-circuits to id-propagation only.
        self.tracer = TraceRecorder(
            sample=trace_sample, slow_ms=trace_slow_ms,
            counter=self._obs["serve_traces_recorded_total"])
        # drain lifecycle: SIGTERM (or begin_drain) flips this, /healthz
        # starts answering 503 draining, admission stops, and drain()
        # waits out the in-flight work
        self._draining = threading.Event()
        self._inflight_lock = threading.Lock()
        self._inflight_http = 0
        self._front = None
        if prefill_chunk and not continuous_slots:
            raise ValueError(
                "--prefill-chunk requires --continuous-slots (chunked "
                "prefill is a slot-engine feature)")
        # in-engine speculative decoding: k draft proposals per slot
        # per round, one multi-query verify — greedy token-exact vs the
        # plain engine. With no --draft-bundle the target SELF-drafts
        # (zero-config but allocates a dense draft shadow cache and
        # saves nothing — deploy a small companion bundle for speed).
        self.spec_tokens = int(spec_tokens)
        if self.spec_tokens and not continuous_slots:
            raise ValueError(
                "--spec-tokens requires --continuous-slots (in-engine "
                "speculation is a slot-engine feature; single-prompt "
                "whole-batch speculation rides --draft-bundle alone)")
        if self.spec_tokens and not draft_bundle_dir:
            logger.warning(
                "--spec-tokens %d without --draft-bundle: SELF-draft "
                "mode (correctness/testing — the dense draft shadow "
                "cache costs memory and the draft forwards cost as "
                "much as the verify; deploy a small draft bundle for "
                "the speedup)", self.spec_tokens)
        # liveness signal thresholds for GET /livez (no engine lock):
        # the driver loop's last-iteration age past live_stall_s flips
        # /livez to 503 — the cheap httpGet form of the heartbeat-age
        # exec probe
        self._live_stall_s = float(live_stall_s)
        # chaos spec: named-point tokens (POINT:ACTION@N / %P — see
        # chaos/inject.FAULT_POINTS) install the process-global
        # ChaosInjector, covering the request front and engine device
        # points on ANY serving mode; legacy fail@N / slow@N:S tokens
        # keep driving the engine DRIVER LOOP via FaultInjector below
        chaos = None
        if chaos_spec:
            from pyspark_tf_gke_tpu.chaos.inject import (
                install as chaos_install,
                split_serve_chaos_spec,
            )

            chaos, named = split_serve_chaos_spec(chaos_spec)
            if named is not None:
                chaos_install(named)
                logger.warning("named-point chaos injection ACTIVE: %s",
                               named.describe())
        if continuous_slots:
            heartbeat = None
            if heartbeat_file:
                from pyspark_tf_gke_tpu.train.resilience import Heartbeat

                # every_steps throttles the idle spin (~20 Hz) to a few
                # writes/sec; a busy loop beats once per engine chunk
                heartbeat = Heartbeat(heartbeat_file, every_steps=5)
            # multi-host: the engine announces each device op over the
            # serving wire (OP_CB_*) and the worker loops replay it into
            # their own SlotDeviceState replicas
            self._front = _ContinuousFront(
                self.model, self.params,
                eos_id=getattr(self.tokenizer, "eos_id", None),
                num_slots=continuous_slots, chunk=continuous_chunk,
                mesh=mesh, announce=self.multi_host,
                prefix_cache_size=prefix_cache_size,
                prefill_chunk=prefill_chunk,
                step_token_budget=step_token_budget,
                pipeline_depth=continuous_pipeline,
                adaptive_chunk=adaptive_chunk,
                schedule=schedule, obs=self._obs,
                event_log=self.event_log,
                max_queue_depth=max_queue_depth,
                max_queued_tokens=max_queued_tokens,
                chaos=chaos, heartbeat=heartbeat,
                tenants=tenants_spec,
                step_timeout_s=step_timeout_s,
                spec_tokens=self.spec_tokens,
                draft_model=self.draft_model,
                draft_params=self.draft_params,
                step_record_ring=step_record_ring,
                peak_flops=peak_flops,
                tracer=self.tracer)

    # -- bundle loading / hot-swap ---------------------------------------

    def _load_and_verify(self, bundle_dir: str):
        """Load + verify one serving bundle into ``(model, params,
        meta, tokenizer)`` — ONE path shared by construction and
        :meth:`reload_bundle`, so a hot-swapped bundle passes exactly
        the checks a boot-time bundle does.

        Loads retry with backoff: a GCS blip or a bundle mid-upload
        should cost seconds, not a CrashLoopBackOff cycle.
        Deterministic config errors fail FAST instead of masquerading
        as storage outages: a mistyped path (FileNotFoundError), a
        corrupt/unsupported config.json (ValueError incl.
        JSONDecodeError, KeyError/TypeError from missing fields)."""
        from pyspark_tf_gke_tpu.data.text import get_tokenizer
        from pyspark_tf_gke_tpu.train.export import load_serving_bundle
        from pyspark_tf_gke_tpu.train.resilience import retry_with_backoff

        _permanent = (FileNotFoundError, ValueError, KeyError, TypeError)

        def _load():
            # chaos: bundle-load fault point inside the retried closure
            # (boot AND hot-swap reload ride this one path)
            chaos_fire("bundle.load", bundle=bundle_dir)
            return load_serving_bundle(bundle_dir)

        model, params, meta = retry_with_backoff(
            _load, op="bundle_load", give_up_on=_permanent)
        if self._int8_kv and not model.cfg.kv_cache_quant:
            # cache layout is a serving-time choice (params unchanged) —
            # allow turning it on for bundles exported without the flag
            import dataclasses

            from pyspark_tf_gke_tpu.models import CausalLM

            model = CausalLM(
                dataclasses.replace(model.cfg, kv_cache_quant=True))
        tokenizer = get_tokenizer(meta.get("tokenizer", "byte"))
        if tokenizer.vocab_size > model.cfg.vocab_size:
            raise ValueError(
                f"bundle tokenizer vocab {tokenizer.vocab_size} exceeds "
                f"model vocab {model.cfg.vocab_size}")
        if (self.draft_model is not None
                and self.draft_model.cfg.vocab_size
                != model.cfg.vocab_size):
            raise ValueError(
                f"bundle vocab {model.cfg.vocab_size} != configured "
                f"draft bundle vocab {self.draft_model.cfg.vocab_size}")
        if self.mesh is not None:
            from pyspark_tf_gke_tpu.train.serving import (
                shard_params_for_serving,
            )

            params = shard_params_for_serving(model, params, self.mesh)
        return model, params, meta, tokenizer

    def _check_swap_compat(self, meta: dict, model) -> None:
        """Hot-swap compatibility: the new bundle must speak the SAME
        request contract as the one serving — tokenizer spec and vocab
        pinned (a request racing the swap may encode under one bundle
        and decode under the other; with these pinned that race is
        harmless). Architecture/size changes within the same contract
        (layers, heads, max_seq_len, kv layout) are fine — the engine
        is rebuilt around the new config. Bigger migrations are a
        blue/green fleet swap, not a hot reload."""
        old_spec = self.meta.get("tokenizer", "byte")
        new_spec = meta.get("tokenizer", "byte")
        if new_spec != old_spec:
            raise ValueError(
                f"incompatible bundle: tokenizer {new_spec!r} != "
                f"serving tokenizer {old_spec!r}")
        if model.cfg.vocab_size != self.model.cfg.vocab_size:
            raise ValueError(
                f"incompatible bundle: vocab {model.cfg.vocab_size} != "
                f"serving vocab {self.model.cfg.vocab_size}")

    def _install_bundle(self, model, params, meta, tokenizer,
                        bundle_dir: str, drain_s: float = 30.0) -> None:
        """Point the serving surfaces at a (verified) bundle. The
        whole-batch path swaps under the device lock; the slot engine
        swaps through :meth:`_ContinuousFront.swap_model` (drains
        in-flight work on the OLD weights, explicit terminals past the
        grace bound, fresh engine after)."""
        with self._lock:
            self.model = model
            self.params = params
            self.meta = meta
            self.tokenizer = tokenizer
            self.bundle_dir = bundle_dir
        if self._front is not None:
            self._front.swap_model(
                model, params, getattr(tokenizer, "eos_id", None),
                drain_s=drain_s)

    def _canary(self) -> None:
        """One tiny generate through the freshly swapped bundle — the
        gate between 'loaded' and 'serving': only after it returns does
        the advertised generation advance. Slot-engine servers probe
        through :meth:`_ContinuousFront.submit_internal`, bypassing the
        admission/quota gates — a canary 429'd by overload would roll
        back a good bundle precisely when the system is busiest."""
        ids = self.tokenizer.encode("canary")
        if self._front is not None:
            rid = self._front.submit_internal(ids, 2)
            self._front.wait(rid, timeout_s=120)
            return
        out = self.generate(["canary"], max_new_tokens=2)
        if not out or "completion" not in out[0]:
            raise RuntimeError(f"canary generate returned {out!r}")

    def reload_bundle(self, bundle_dir: str, generation=None,
                      canary: bool = True,
                      drain_s: float = 30.0) -> dict:
        """Hot-swap to the bundle at ``bundle_dir`` (the pipeline
        coordinator's publish path; ``POST /admin/reload``).

        Sequence: load+verify off the driver thread (same retried path
        as boot) → compat check → swap in (in-flight work drains on the
        old weights) → canary generate → advance the advertised
        ``bundle_generation``. A load/compat failure swaps NOTHING; a
        canary failure reinstalls the previous bundle — either way the
        old generation keeps serving and the error is typed
        (:class:`BundleReloadError`, HTTP 502). One reload at a time
        (:class:`ReloadInFlight`, HTTP 409). Single-host only: a
        multi-host swap needs the params re-announced to every worker
        replica — roll the pods instead."""
        if self.multi_host:
            raise ValueError(
                "bundle hot-swap is single-host only — multi-host "
                "fleets roll pods through the k8s rolling update")
        if generation is not None:
            # coerce BEFORE any swap: a malformed generation failing
            # after the canary would leave the new bundle serving with
            # the advertised generation never advanced
            try:
                generation = int(generation)
            except (TypeError, ValueError):
                raise ValueError(
                    f"'generation' must be an integer, got "
                    f"{generation!r}") from None
        if not self._reload_lock.acquire(blocking=False):
            raise ReloadInFlight(
                "a bundle reload is already in flight; retry after it "
                "settles")
        try:
            self.event_log.emit("bundle_reload_started",
                                bundle=bundle_dir,
                                current_generation=self.bundle_generation)
            old = (self.model, self.params, self.meta, self.tokenizer,
                   self.bundle_dir)
            try:
                model, params, meta, tokenizer = (
                    self._load_and_verify(bundle_dir))
                self._check_swap_compat(meta, model)
            except Exception as exc:
                self._obs["serve_bundle_reloads_total"].labels(
                    outcome="rejected").inc()
                self.event_log.emit(
                    "bundle_reload_failed", bundle=bundle_dir,
                    rolled_back=False,
                    error=f"{type(exc).__name__}: {exc}"[:500])
                raise BundleReloadError(
                    f"bundle rejected before swap: {exc}",
                    rolled_back=False) from exc
            self._install_bundle(model, params, meta, tokenizer,
                                 bundle_dir, drain_s=drain_s)
            if canary:
                try:
                    self._canary()
                except Exception as exc:  # noqa: BLE001 — any canary
                    # failure must leave the OLD generation serving
                    logger.exception(
                        "canary generate failed after bundle swap; "
                        "rolling back to %s", old[4])
                    self._install_bundle(*old, drain_s=drain_s)
                    self._obs["serve_bundle_reloads_total"].labels(
                        outcome="rolled_back").inc()
                    self.event_log.emit(
                        "bundle_reload_rolled_back", bundle=bundle_dir,
                        restored=old[4],
                        error=f"{type(exc).__name__}: {exc}"[:500])
                    raise BundleReloadError(
                        f"canary generate failed (previous bundle "
                        f"restored): {exc}", rolled_back=True) from exc
            gen = (generation if generation is not None
                   else int(meta.get("pipeline_generation",
                                     self.bundle_generation + 1)))
            self.bundle_generation = gen
            self._obs["serve_bundle_generation"].set(gen)
            self._obs["serve_bundle_reloads_total"].labels(
                outcome="ok").inc()
            self.event_log.emit("bundle_reload_succeeded",
                                bundle=bundle_dir, generation=gen,
                                canary=bool(canary))
            logger.info("bundle hot-swapped: %s (generation %d)",
                        bundle_dir, gen)
            return {"ok": True, "bundle": bundle_dir,
                    "bundle_generation": gen, "canary": bool(canary)}
        finally:
            self._reload_lock.release()

    # -- drain lifecycle -------------------------------------------------

    def start_profile(self, output_dir: Optional[str],
                      steps: int = 8) -> dict:
        """On-demand profiler capture (``POST /admin/profile``, admin-
        token-gated like ``/admin/reload``): arm a ``jax.profiler``
        trace over the next ``steps`` BUSY engine steps, written to
        ``output_dir`` (a fresh temp dir when omitted — the response
        says where). Asynchronous: returns as soon as the capture is
        armed; completion lands on the event trail as
        ``profile_trace_written`` with the covered step-seq window and
        recent trace ids. Raises :class:`ProfileInFlight` (409) while
        a capture is armed/running, :class:`ValueError` (400) on a
        whole-batch server (no step loop to profile)."""
        if self._front is None:
            raise ValueError(
                "profiling requires --continuous-slots (the capture "
                "spans engine steps; whole-batch serving has no step "
                "loop)")
        # validate + in-flight precheck BEFORE touching the filesystem
        # (a client polling the endpoint while a capture runs must not
        # leak one orphan temp dir per 409); the front's LOCKED check
        # stays authoritative — if two arms race past the precheck,
        # the loser's fresh temp dir is removed again below
        if int(steps) < 1:
            raise ValueError(f"profile steps must be >= 1, got {steps}")
        if self._front.profile_in_flight():
            raise ProfileInFlight(
                "a profiler capture is already in flight")
        created = None
        if not output_dir:
            import tempfile

            output_dir = tempfile.mkdtemp(prefix="stepprof-")
            created = output_dir
        else:
            os.makedirs(output_dir, exist_ok=True)
        try:
            return self._front.start_profile(output_dir, steps)
        except ProfileInFlight:
            if created is not None:
                import contextlib

                with contextlib.suppress(OSError):
                    os.rmdir(created)
            raise

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def begin_drain(self) -> None:
        """Flip to draining: /healthz readiness goes 503 (k8s stops
        routing), admission stops (new requests get 503 + Retry-After),
        in-flight requests keep decoding. Idempotent."""
        if self._draining.is_set():
            return
        self._draining.set()
        self._obs["serve_draining"].set(1)
        self.event_log.emit("serve_drain_started", bundle=self.bundle_dir)
        if self._front is not None:
            self._front.begin_drain()

    def _http_enter(self) -> None:
        with self._inflight_lock:
            self._inflight_http += 1

    def _http_exit(self) -> None:
        with self._inflight_lock:
            self._inflight_http -= 1

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait for every in-flight HTTP request AND the slot engine to
        finish, up to ``timeout_s``. Returns True when fully drained —
        the CLI then exits 0; False means the grace window expired with
        work still in flight (k8s SIGKILL follows; the trail records
        it)."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._inflight_lock:
                busy_http = self._inflight_http
            front_idle = (self._front is None
                          or self._front.drain(timeout_s=0))
            if not busy_http and front_idle:
                self.event_log.emit("serve_drain_finished", drained=True)
                return True
            if time.monotonic() >= deadline:
                self.event_log.emit(
                    "serve_drain_finished", drained=False,
                    inflight_http=busy_http)
                return False
            time.sleep(0.05)

    # -- health ----------------------------------------------------------

    def health(self) -> dict:
        return {
            "status": "draining" if self.draining else "ok",
            "bundle": self.bundle_dir,
            "bundle_generation": self.bundle_generation,
            "model": self.meta.get("model"),
            "quantized": bool(self.meta.get("quantized")),
            "vocab_size": self.model.cfg.vocab_size,
            "max_seq_len": self.model.cfg.max_seq_len,
            "tokenizer": self.meta.get("tokenizer", "byte"),
            "n_devices": len(jax.devices()),
            "processes": jax.process_count(),
            "tp": dict(self.mesh.shape).get("tp", 1) if self.mesh else 1,
            "speculative_draft": self.draft_bundle_dir or None,
            "draining": self.draining,
            "admission": ({"max_queue_depth": self._front.max_queue_depth,
                           "max_queued_tokens":
                               self._front.max_queued_tokens}
                          if self._front is not None else None),
            "continuous": (self._front.engine.stats
                           if self._front is not None else None),
        }

    def livez(self) -> dict:
        """Pure LIVENESS (``GET /livez``): is this PROCESS worth
        keeping, independent of readiness/load. Touches NO engine
        state and takes NO lock — a wedged engine must not wedge the
        probe that exists to detect it. ``live`` goes false only when
        the slot engine's driver loop has not completed an iteration
        for ``live_stall_s`` (a hung device dispatch the watchdog
        couldn't clear) — draining, zero capacity, or a dead backend
        are readiness verdicts (/healthz, /loadz), never liveness.
        Whole-batch servers (no driver loop) are always live."""
        out = {"live": True, "draining": self.draining}
        front = self._front
        if front is not None:
            age = time.monotonic() - front._last_loop_ts
            out["driver_loop_age_s"] = round(age, 3)
            out["wedged"] = bool(front._wedged)
            out["step_timeout_s"] = front.step_timeout_s
            if self._live_stall_s and age > self._live_stall_s:
                out["live"] = False
        return out

    def loadz(self) -> dict:
        """One cheap JSON load snapshot (``GET /loadz``): what the
        replica router's prober polls instead of scraping Prometheus
        text. The key set is a STABLE contract (tests pin it) — the
        router scores replicas by ``queued_tokens``/``active`` and
        gates on ``draining``; whole-batch servers (no slot engine)
        report zeros so the router can still rank them by in-flight
        HTTP load. ``capacity_free`` (routable token headroom, the
        tightest of the admission-token budget and the KV page pool),
        ``queue_delay_ms`` (oldest queued request's age) and the
        per-tenant ``tenants`` map feed the router's closed-loop
        autoscale signal and per-tenant dashboards."""
        with self._inflight_lock:
            inflight_http = self._inflight_http
        out = {
            "queued": 0,
            "queued_tokens": 0,
            "active": 0,
            "slots_total": 0,
            "kv_pages_free": None,
            "inflight_http": inflight_http,
            "draining": self.draining,
            # hot-swap rollout signal: advances only after a successful
            # swap + canary, so the coordinator's publish confirmation
            # and the router's prober read the SERVING generation
            "bundle_generation": self.bundle_generation,
            # disaggregated serving role (--role / SERVE_ROLE): the
            # router's role-split policy keys off this — prefill
            # replicas take long-prompt handoffs, decode/mixed take
            # generate traffic
            "role": self.role,
            # radix prefix cache: ACTUAL cache contents + measured hit
            # rate, so the router's affinity can score on what the
            # replica really holds instead of hashed ownership alone
            "prefix_cache_pages": 0,
            "prefix_hit_rate": 0.0,
            # autoscale/tenancy terms (zeros for whole-batch servers:
            # no admission queue to have headroom or delay in)
            "capacity_free": 0,
            "queue_delay_ms": 0.0,
            "tenants": {},
            # in-engine speculative decoding: windowed draft acceptance
            # (0.0 when --spec-tokens is off) — speculation quality a
            # router/capacity model can score on
            "spec_accept_rate": 0.0,
            # step telemetry (obs/stepstats.py): windowed DEVICE-IDLE
            # fraction of the engine step loop, derived from per-chunk
            # dispatch/retire timestamps (1 - union(device-busy)/span;
            # on a serial loop this matches the historical
            # host-work-share formula, which rides the same summary as
            # step_phases.host_work_frac) — the router's autoscale
            # block takes the fleet max, replay/capacity calibration
            # records it next to the measured service rates, and the
            # async engine core is A/B'd against it (0.0 for
            # whole-batch servers / before the first step)
            "step_host_overhead_frac": 0.0,
            # windowed engine throughput from the same /stepz summary —
            # the router watchtower's fleet rollup sums it
            # (step_tokens_per_sec_total on GET /fleetz) without a
            # second probe round-trip
            "step_tokens_per_sec": 0.0,
        }
        if self._front is not None:
            stats = self._front.engine.stats
            out["queued"] = stats["queued"]
            out["queued_tokens"] = stats["queued_tokens"]
            out["active"] = stats["active"]
            out["slots_total"] = stats["num_slots"]
            out["queue_delay_ms"] = stats.get("queue_delay_ms", 0.0)
            paged = stats.get("paged")
            if paged:
                out["kv_pages_free"] = (paged["pages_total"]
                                        - paged["pages_in_use"])
            cache = stats.get("prefix_cache")
            if cache:
                out["prefix_cache_pages"] = int(
                    cache.get("resident_pages", 0))
                out["prefix_hit_rate"] = float(
                    cache.get("recent_hit_rate", 0.0))
            # routable token headroom: how many more prompt+budget
            # tokens this replica would ADMIT right now — the tightest
            # of the bounded-admission budget and (paged engines) the
            # free KV pages' token extent; an unbounded dense engine
            # falls back to free slots x max_seq_len (crude but
            # monotone in real headroom)
            caps = []
            if self._front.max_queued_tokens:
                caps.append(self._front.max_queued_tokens
                            - stats["queued_tokens"])
            if paged:
                caps.append((paged["pages_total"]
                             - paged["pages_in_use"])
                            * paged["page_size"])
            if not caps:
                caps.append((stats["num_slots"] - stats["active"])
                            * self.model.cfg.max_seq_len)
            out["capacity_free"] = max(0, min(caps))
            self._obs["serve_capacity_free_tokens"].set(
                out["capacity_free"])
            if self.spec_tokens:
                out["spec_accept_rate"] = round(
                    self._front.engine.spec_accept_rate(), 4)
            # from the stats snapshot already in hand (summary() pre-
            # rounds it) — no second ring-lock pass per /loadz probe
            out["step_host_overhead_frac"] = (
                stats["step_phases"]["host_overhead_frac"])
            out["step_tokens_per_sec"] = (
                stats["step_phases"].get("tokens_per_sec") or 0.0)
            tenants = {}
            for name, t in (stats.get("tenants") or {}).items():
                tenants[name] = {"queued": t["queued"],
                                 "queued_tokens": t["queued_tokens"]}
                self._obs["serve_tenant_queue_depth"].labels(
                    tenant=name).set(t["queued"])
            out["tenants"] = tenants
        return out

    # -- generation ------------------------------------------------------

    def generate(self, prompts, max_new_tokens: int = 64,
                 temperature: float = 0.0, top_k=None, top_p=None,
                 num_beams: int = 0, repetition_penalty=None,
                 deadline_s=None, tenant: str = "default",
                 seed=None, span=None) -> list:
        """Batch completion. Prompts are grouped by token length so each
        group decodes as one batched call; the batch dimension pads up
        to power-of-2 buckets (repeating the first row) so mixed traffic
        reuses a handful of compiled shapes instead of recompiling per
        group size; results return in input order. Sampling requests get
        a fresh per-request PRNG key — a fixed server-side seed would
        hand every client the same 'random' completion — unless the
        CLIENT pins ``seed`` (the ``/v1/generate`` body field): on the
        slot-engine path each prompt's sampling lane draws from its own
        ``seed + index`` key, so the completion is deterministic per
        (prompt, seed) pair — what makes idempotent retries,
        record/replay and sampled-lane continuations reproducible. The
        whole-batch fallback (beams/top-k/repetition-penalty, or no
        --continuous-slots) shares ONE ``PRNGKey(seed)`` across the
        padded batch: deterministic per (batch, seed), but a prompt's
        draws there depend on its batch composition. Greedy requests
        ignore ``seed`` entirely (byte-identical with or without it).

        ``deadline_s``: seconds from now the client still wants the
        answer (HTTP ``deadline_ms`` / 1000). The slot engine enforces
        it at chunk boundaries (queued requests expire before admission,
        in-slot ones free their KV slot); the whole-batch path checks
        between length groups — both raise :class:`DeadlineExceeded`."""
        from pyspark_tf_gke_tpu.models.causal_lm import generate
        from pyspark_tf_gke_tpu.train.serving import serve_generate

        if self.draining:
            self._obs["serve_requests_rejected_total"].labels(
                reason="draining").inc()
            raise _draining_rejection()
        t_deadline = None
        if deadline_s is not None:
            if deadline_s <= 0:
                self._obs["serve_request_deadline_exceeded_total"].inc()
                raise DeadlineExceeded(
                    f"deadline of {deadline_s * 1000.0:.0f}ms already "
                    "expired at submission")
            t_deadline = time.monotonic() + float(deadline_s)
        if not prompts:
            return []
        if len(prompts) > MAX_BATCH:
            raise ValueError(f"batch of {len(prompts)} exceeds "
                             f"max batch {MAX_BATCH}")
        rng = (jax.random.PRNGKey(
            int(seed) if seed is not None
            else int.from_bytes(os.urandom(4), "little"))
            if temperature and temperature > 0 else None)
        cfg = self.model.cfg
        eos_id = getattr(self.tokenizer, "eos_id", None)
        encoded = []
        for i, text in enumerate(prompts):
            ids = self.tokenizer.encode(text)
            if not ids:
                raise ValueError(f"prompt {i} tokenized to zero tokens")
            if len(ids) + max_new_tokens > cfg.max_seq_len:
                raise ValueError(
                    f"prompt {i}: {len(ids)} tokens + {max_new_tokens} new "
                    f"exceeds max_seq_len {cfg.max_seq_len}")
            encoded.append((i, ids))

        plain_greedy = (not (temperature and temperature > 0)
                        and not num_beams and repetition_penalty is None
                        and top_k is None and top_p is None)
        # the slot engine also serves temperature/top-p sampling (each
        # slot draws with its own per-request key); beams, top-k and
        # repetition penalty stay on the whole-batch path
        engine_ok = (not num_beams and repetition_penalty is None
                     and top_k is None)
        # Routing order for plain-greedy traffic: speculative (when a
        # draft is configured AND its context fits this request) →
        # continuous slot engine → whole-batch. The draft-context check
        # lives HERE so a too-long-for-the-draft request still gets the
        # slot engine instead of a solo whole-batch call.
        # a deadline-bearing request skips speculation: the spec loop
        # has no chunk boundary to cancel at, so it would decode its
        # full budget past a dead client — the slot engine (or the
        # group-checked whole-batch path) enforces deadlines instead
        # --spec-tokens > 0: the SLOT ENGINE speculates in-slot for
        # every request (batched draft/verify with fairness, deadlines
        # and streaming intact), so the standalone single-prompt spec
        # route stands down — it would serialize the pool behind one
        # whole-batch-style call for no extra speed.
        could_spec = (self.draft_model is not None and len(prompts) == 1
                      and plain_greedy and deadline_s is None
                      and not (self.spec_tokens and self._front
                               is not None)
                      and len(encoded[0][1]) + max_new_tokens
                      <= self.draft_model.cfg.max_seq_len)
        if self._front is not None and engine_ok and not could_spec:
            # slot engine: each prompt is its own request — they share
            # KV slots with every OTHER in-flight HTTP request, and a
            # short completion returns without waiting for a long one.
            t0 = time.perf_counter()
            # submit everything first (non-blocking — they co-occupy
            # slots), then collect in order; no thread pool needed to
            # block on events.
            temp = float(temperature or 0.0)
            rids = []
            try:
                for i, ids in encoded:
                    rids.append((i, self._front.submit(
                        ids, max_new_tokens, temperature=temp,
                        top_p=top_p,
                        # client-pinned seed (per-prompt: seed + index)
                        # makes the slot's sampling lane deterministic
                        # end to end — it rides the OP_CB_ADMIT wire as
                        # its own int64, so record/replay and worker
                        # replicas draw the identical stream
                        seed=(int(seed) + i if seed is not None
                              else int.from_bytes(os.urandom(4),
                                                  "little")),
                        deadline_s=deadline_s, tenant=tenant,
                        span=span)))
            except Exception:
                # a mid-batch rejection (queue filled between rows) must
                # not strand the rows already submitted
                for _, rid in rids:
                    self._front.abandon(rid)
                raise
            toks = {}
            try:
                for i, rid in rids:
                    toks[i] = self._front.wait(rid)
            except Exception:
                # one failed wait must not leak its siblings: cancel
                # every uncollected request (frees KV slots + results
                # entries) before surfacing the error as this HTTP 500
                for i, rid in rids:
                    if i not in toks:
                        self._front.abandon(rid)
                raise
            dt = (time.perf_counter() - t0) * 1000.0
            return [self._entry(prompts[i], toks[i], dt, eos_id)
                    for i, _ in rids]

        if could_spec:
            _, ids = encoded[0]
            from pyspark_tf_gke_tpu.train.serving import mh_speculative

            with self._lock:
                t0 = time.perf_counter()
                # mh_speculative owns single-vs-multi-host dispatch (the
                # announce header rides OP_SPECULATIVE; workers replay
                # the same accept/rollback loop in lockstep)
                out, stats = mh_speculative(
                    self.model, self.params, self.draft_model,
                    self.draft_params, jnp.asarray([ids], jnp.int32),
                    self.mesh, max_new_tokens=max_new_tokens,
                    gamma=SPEC_GAMMA, eos_token_id=eos_id)
                dt = (time.perf_counter() - t0) * 1000.0
            return [self._entry(
                prompts[0], np.asarray(as_host_array(out)[0, len(ids):]).tolist(), dt,
                eos_id,
                speculative={
                    "gamma": SPEC_GAMMA,
                    "acceptance_rate": round(
                        stats["accepted"] / max(stats["proposed"], 1), 3),
                    "tokens_per_round": round(stats["tokens_per_round"], 2),
                })]

        groups = {}
        for i, ids in encoded:
            groups.setdefault(len(ids), []).append((i, ids))

        results = [None] * len(prompts)
        with self._lock:
            for length, members in sorted(groups.items()):
                if t_deadline is not None and time.monotonic() > t_deadline:
                    # whole-batch granularity: between length groups (a
                    # dispatched group runs to completion — the compiled
                    # scan has no host re-entry to cancel at)
                    self._obs["serve_request_deadline_exceeded_total"].inc()
                    raise DeadlineExceeded(
                        "request deadline exceeded before the batch "
                        "finished decoding")
                rows = [ids for _, ids in members]
                n_real = len(rows)
                bucket = 1 << (n_real - 1).bit_length()  # next power of 2
                rows = rows + [rows[0]] * (bucket - n_real)
                batch = jnp.asarray(rows, jnp.int32)
                t0 = time.perf_counter()
                if num_beams and num_beams > 1:
                    from pyspark_tf_gke_tpu.train.serving import mh_generate

                    # mh_generate owns single-vs-multi-host dispatch and
                    # the shared serve_beam gather sequence
                    out, scores = mh_generate(
                        self.model, self.params, batch, self.mesh,
                        max_new_tokens=max_new_tokens, eos_token_id=eos_id,
                        num_beams=num_beams)
                    scores = np.asarray(scores)
                elif self.multi_host:
                    from pyspark_tf_gke_tpu.train.serving import mh_generate

                    # everything (incl. the rng key for sampling) rides
                    # the announce/replay wire — see train/serving.py
                    out = mh_generate(self.model, self.params, batch,
                                      self.mesh,
                                      max_new_tokens=max_new_tokens,
                                      eos_token_id=eos_id,
                                      temperature=temperature,
                                      top_k=top_k, top_p=top_p,
                                      repetition_penalty=repetition_penalty,
                                      rng=rng)
                    scores = None
                else:
                    gen_fn = generate if self.mesh is None else serve_generate
                    kwargs = {} if self.mesh is None else {"mesh": self.mesh}
                    out = gen_fn(
                        self.model, self.params, batch,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, rng=rng, top_k=top_k,
                        top_p=top_p, eos_token_id=eos_id,
                        repetition_penalty=repetition_penalty, **kwargs)
                    scores = None
                toks = np.asarray(as_host_array(out))[:n_real, length:]
                dt = (time.perf_counter() - t0) * 1000.0
                for row, (i, _) in enumerate(members):
                    extra = ({"beam_score": float(scores[row])}
                             if scores is not None else {})
                    results[i] = self._entry(prompts[i], toks[row].tolist(),
                                             dt, eos_id, **extra)
        return results

    def warm_prefix(self, prefix: str) -> dict:
        """Tokenize + prefill a shared prompt prefix into the slot
        engine's prefix cache (the /v1/warm endpoint). Later greedy
        requests whose prompt starts with it skip that prefill."""
        if self._front is None:
            raise ValueError("warming requires --continuous-slots")
        ids = self.tokenizer.encode(prefix)
        if not ids:
            raise ValueError("prefix tokenized to zero tokens")
        n = self._front.warm_prefix(ids)
        return {"prefix_tokens": n,
                "prefix_cache": self._front.engine.stats.get(
                    "prefix_cache")}

    # -- disaggregated prefill/decode (docs/SERVING.md) ------------------

    def prefill_export(self, prompt: str) -> dict:
        """``POST /v1/prefill``: chunked-prefill the prompt into the
        radix cache and export the finished KV pages as one base64
        ``.npz`` page blob — the prefill replica's half of a
        disaggregated handoff. The caller (the router) ships the blob
        to a decode replica's ``/v1/kv_import``; only FULL pages
        travel, the decode-side admission re-prefills the tail
        remainder exactly like a local radix hit. A repeat prompt is
        already cached, so the export is the only device work."""
        import base64

        from pyspark_tf_gke_tpu.train.kv_transfer import pack_kv_export

        if self._front is None:
            raise ValueError("KV export requires --continuous-slots")
        ids = self.tokenizer.encode(prompt)
        if not ids:
            raise ValueError("prompt tokenized to zero tokens")
        warmed = self._front.warm_prefix(ids)
        export = self._front.export_prefix_pages(ids)
        if export is None:
            # prompt shorter than one KV page: nothing transferable —
            # the router falls back to the normal (RECOMPUTE) path
            return {"prefix_tokens": warmed, "page_size": 0,
                    "pages": 0, "blob": None}
        blob = pack_kv_export(export)
        self._obs["serve_kv_xfer_bytes_total"].inc(len(blob))
        return {
            "prefix_tokens": warmed,
            "page_size": export["page_size"],
            "pages": len(export["token_ids"]) // export["page_size"],
            "blob": base64.b64encode(blob).decode("ascii"),
        }

    def kv_import(self, blob_b64: str) -> dict:
        """``POST /v1/kv_import``: install a transferred KV page blob
        into this replica's pool and adopt it into the radix trie —
        the decode replica's half of a disaggregated handoff. One
        import warms every follower of the prefix; re-imports are
        idempotent (resident pages are reused, not re-written)."""
        import base64

        from pyspark_tf_gke_tpu.train.kv_transfer import unpack_kv_blob

        if self._front is None:
            raise ValueError("KV import requires --continuous-slots")
        data = base64.b64decode(blob_b64.encode("ascii"),
                                validate=True)
        self._obs["serve_kv_xfer_bytes_total"].inc(len(data))
        transfer = unpack_kv_blob(data)
        ps = getattr(self.model.cfg, "kv_page_size", None)
        if ps is None or transfer["page_size"] != ps:
            raise ValueError(
                f"KV transfer page_size {transfer['page_size']} does "
                f"not match this replica's kv_page_size {ps} — "
                "role-split fleets must serve one bundle shape")
        imported = self._front.import_prefix_pages(
            transfer["token_ids"], transfer["layers"])
        return {"imported_tokens": imported,
                "pages": imported // ps if ps else 0}

    def generate_stream(self, prompt: str, max_new_tokens: int = 64,
                        deadline_s=None, tenant: str = "default",
                        continuation=None, span=None):
        """Greedy streaming completion through the slot engine: yields
        one event dict per decoded token group (``token_ids`` plus the
        full ``text`` so far — full text, not a delta, so multibyte
        tokenizer sequences can't tear), then a terminal event with the
        assembled completion. Requires --continuous-slots.

        ``continuation`` (``{"emitted_ids": [int, ...]}``): the
        router's mid-stream failover splice. ``prompt`` is the
        ORIGINAL prompt and ``emitted_ids`` the token ids a dead
        replica already delivered: the engine prefills
        ``encode(prompt) + emitted_ids`` (token-EXACT — text-level
        re-tokenization would be lossy for non-UTF-8 byte runs) and
        greedy decode continues precisely where the dead stream
        stopped. Events and the terminal entry frame text/counts
        CUMULATIVELY (``text`` = prompt + decode(emitted + new),
        ``new_tokens`` = emitted + generated), so a client splicing
        this leg after the originals sees one uninterrupted run."""
        if self._front is None:
            raise ValueError(
                "streaming requires --continuous-slots (the slot engine "
                "is what yields tokens as they decode)")
        if deadline_s is not None and deadline_s <= 0:
            # same contract as the blocking path: an already-dead
            # deadline is a 504 + the deadline counter, not a 400
            # leaking the internal parameter name
            self._obs["serve_request_deadline_exceeded_total"].inc()
            raise DeadlineExceeded(
                f"deadline of {deadline_s * 1000.0:.0f}ms already "
                "expired at submission")
        ids = self.tokenizer.encode(prompt)
        if not ids:
            raise ValueError("prompt tokenized to zero tokens")
        prior_ids: list = []
        if continuation is not None:
            # token-id splice point: prefill = prompt ids + the ids the
            # dead replica already delivered (NOT re-tokenized text —
            # decode→encode is lossy for non-UTF-8 byte runs)
            prior_ids = [int(t) for t in continuation["emitted_ids"]]
            ids = ids + prior_ids
            if span is not None:
                # the resume crosses replicas inside ONE trace: the
                # router's `resume` event names the dead leg, this one
                # marks where the continuation picked up
                span.event("continuation",
                           emitted_tokens=len(prior_ids))
        cfg = self.model.cfg
        if len(ids) + max_new_tokens > cfg.max_seq_len:
            raise ValueError(
                f"{len(ids)} tokens + {max_new_tokens} new exceeds "
                f"max_seq_len {cfg.max_seq_len}")
        eos_id = getattr(self.tokenizer, "eos_id", None)
        t0 = time.perf_counter()
        rid, q = self._front.submit_stream(ids, max_new_tokens,
                                           deadline_s=deadline_s,
                                           tenant=tenant, span=span)
        toks, finished, yielded = [], False, False
        try:
            while True:
                item = q.get(timeout=600)
                if isinstance(item, Exception):
                    if isinstance(item, (DeadlineExceeded,
                                         EngineShutdown,
                                         RequestRejected)):
                        raise item
                    raise RuntimeError(
                        f"continuous engine failed this request: {item}")
                if item == []:
                    break
                if eos_id is not None and eos_id in item:
                    item = item[:item.index(eos_id)]
                    toks.extend(item)
                    if item:
                        yielded = True
                        yield {"token_ids": item,
                               "text": prompt + self.tokenizer.decode(
                                   prior_ids + toks)}
                    break
                toks.extend(item)
                yielded = True
                yield {"token_ids": item,
                       "text": prompt + self.tokenizer.decode(
                           prior_ids + toks)}
            # collect + release the results entry (event already set by
            # the time the terminal item arrives; short timeout)
            self._front.wait(rid, timeout_s=60)
            finished = True
        finally:
            if not finished:
                self._front.abandon(rid)
                exc_type = sys.exc_info()[0]
                if (not yielded and exc_type is not None and issubclass(
                        exc_type, (DeadlineExceeded, RequestRejected))):
                    # expired/rejected BEFORE the first event: the
                    # exception propagates to the HTTP handler, which
                    # does this request's accounting (504/503 + the
                    # dedicated counters) — counting here too would
                    # double-book serve_requests_total and brand a shed
                    # request as a server failure
                    pass
                else:
                    # engine failure or client disconnect mid-stream:
                    # the 200 is already committed, so /metrics is the
                    # only place this failure can still be seen
                    self.record_metrics(failed=True)
        entry = {
            "prompt": prompt,
            "completion": prompt + self.tokenizer.decode(
                prior_ids + toks),
            "new_tokens": len(prior_ids) + len(toks),
            "latency_ms": round((time.perf_counter() - t0) * 1000.0, 2),
            "done": True,
        }
        if continuation is not None:
            entry["resumed"] = True
        # metrics count what THIS replica generated (a continuation's
        # prior tokens were another replica's work — counting them here
        # would double-book serve_generate_tokens_total fleet-wide)
        self.record_metrics(generate_entries=[
            {**entry, "new_tokens": len(toks)}],
            trace_id=(span.trace_id
                      if span is not None else None))
        yield entry

    def record_metrics(self, *, generate_entries=None, score: bool = False,
                       failed: bool = False,
                       trace_id: Optional[str] = None) -> None:
        """Fold one request into the shared registry (handler-thread
        safe — every metric holds its own lock). ``trace_id`` rides the
        latency histogram as the bucket's exemplar: the JSON snapshot
        links each latency bucket to a concrete trace in /traces."""
        m = self._obs
        m["serve_requests_total"].inc()
        if failed:
            m["serve_requests_failed_total"].inc()
        if score:
            m["serve_score_requests_total"].inc()
        if generate_entries:
            m["serve_generate_requests_total"].inc()
            m["serve_generate_tokens_total"].inc(sum(
                e.get("new_tokens", 0) for e in generate_entries))
            m["serve_generate_latency_ms"].observe(max(
                (e.get("latency_ms", 0.0) for e in generate_entries),
                default=0.0), exemplar=trace_id)

    def _legacy_metrics_text(self) -> str:
        """The pre-obs exposition names, aliased onto registry values —
        a strict superset guarantee for existing scrape configs. New
        dashboards should use the canonical ``serve_*`` families."""
        m = self._obs
        alias = [
            ("requests_total", "counter", m["serve_requests_total"].value),
            ("requests_failed_total", "counter",
             m["serve_requests_failed_total"].value),
            ("generate_tokens_total", "counter",
             m["serve_generate_tokens_total"].value),
            ("generate_latency_ms_sum", "counter",
             m["serve_generate_latency_ms"].sum),
            ("generate_requests_total", "counter",
             m["serve_generate_requests_total"].value),
            ("score_requests_total", "counter",
             m["serve_score_requests_total"].value),
        ]
        lines = []
        for key, kind, val in alias:
            name = f"pyspark_tf_gke_tpu_serve_{key}"
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} "
                         f"{int(val) if float(val).is_integer() else val}")
        if self._front is not None:
            stats = self._front.engine.stats
            for key in ("queued", "active", "finished", "num_slots"):
                name = f"pyspark_tf_gke_tpu_serve_continuous_{key}"
                kind = "counter" if key == "finished" else "gauge"
                lines.append(f"# TYPE {name} {kind}")
                lines.append(f"{name} {stats[key]}")
            for key, val in (stats.get("prefix_cache") or {}).items():
                if not isinstance(val, (int, float)):
                    continue  # the radix stats carry a "kind" tag —
                    #           not a number, not exposable
                name = ("pyspark_tf_gke_tpu_serve_continuous_"
                        f"prefix_cache_{key}")
                kind = ("counter" if key in ("hits", "misses",
                                             "hit_tokens", "evictions")
                        else "gauge")
                lines.append(f"# TYPE {name} {kind}")
                lines.append(f"{name} {val}")
        return "\n".join(lines) + "\n"

    def _refresh_engine_gauges(self) -> None:
        """Pull-model scrape prep: the engine only updates its gauges
        at collect boundaries, so re-read them at exposition time."""
        if self._front is not None:
            stats = self._front.engine.stats
            self._obs["serve_slots_total"].set(stats["num_slots"])
            self._obs["serve_slots_active"].set(stats["active"])
            self._obs["serve_queue_depth"].set(stats["queued"])
            for name, t in (stats.get("tenants") or {}).items():
                self._obs["serve_tenant_queue_depth"].labels(
                    tenant=name).set(t["queued"])

    def metrics_text(self) -> str:
        """Prometheus exposition text: the full shared registry
        (train_/serve_/runtime_ families) plus the legacy alias block."""
        self._refresh_engine_gauges()
        return self.registry.exposition() + self._legacy_metrics_text()

    def _entry(self, prompt, new_tokens, dt_ms, eos_id, **extra) -> dict:
        """Shared response assembly: eos truncation + decode back to
        text (one definition for the batched and speculative paths)."""
        if eos_id is not None and eos_id in new_tokens:
            new_tokens = new_tokens[:new_tokens.index(eos_id)]
        return {
            "prompt": prompt,
            "completion": prompt + self.tokenizer.decode(new_tokens),
            "new_tokens": len(new_tokens),
            "latency_ms": round(dt_ms, 2),
            **extra,
        }

    # -- scoring ---------------------------------------------------------

    def score(self, texts, tenant: str = "default") -> list:
        """Per-text total NLL in nats + scored token count. Texts longer
        than max_seq_len are truncated (reported via ``truncated``);
        texts shorter than 2 tokens have no next-token NLL and come back
        ``{"skipped": true, "tokens": 0}`` rather than failing the
        batch (remote perplexity eval feeds arbitrary documents).
        With a ``--tenants`` spec, the batch's scored-token total is
        charged against the tenant's quota bucket up front (exact
        work, no refund) — score is not an unmetered side door around
        a generate throttle."""
        if not texts:
            return []
        if len(texts) > MAX_BATCH:
            raise ValueError(f"batch of {len(texts)} exceeds "
                             f"max batch {MAX_BATCH}")
        cap = self.model.cfg.max_seq_len
        results = [None] * len(texts)
        rows = []  # (result index, ids, truncated)
        for i, text in enumerate(texts):
            ids = self.tokenizer.encode(text)
            if len(ids) < 2:
                results[i] = {"nll": 0.0, "tokens": 0, "truncated": False,
                              "skipped": True}
                continue
            rows.append((i, ids[:cap], len(ids) > cap))
        if rows and self._front is not None:
            self._front.charge_tokens(
                tenant, sum(len(ids) for _, ids, _ in rows))
        if rows:
            lengths = [len(ids) for _, ids, _ in rows]
            seq_len = _bucket(max(lengths), cap)
            # batch dim pads to a power-of-2 bucket too (dummy rows get
            # length 0 → fully masked), bounding compiled shapes
            n_real = len(rows)
            n_bucket = 1 << (n_real - 1).bit_length()
            padded = np.zeros((n_bucket, seq_len), np.int32)
            for r, (_, ids, _) in enumerate(rows):
                padded[r, :len(ids)] = ids
            lengths = lengths + [0] * (n_bucket - n_real)
            from pyspark_tf_gke_tpu.train.serving import mh_score

            with self._lock:
                # mh_score owns the single-vs-multi-host dispatch: it
                # announces for workers to replay when processes > 1 and
                # degrades to plain serve_score otherwise
                nlls = np.asarray(mh_score(
                    self.model, self.params, padded, lengths, self.mesh))
            for r, (i, ids, trunc) in enumerate(rows):
                results[i] = {"nll": float(nlls[r]), "tokens": len(ids) - 1,
                              "truncated": trunc}
        return results


# -- HTTP plumbing -----------------------------------------------------------


def _span_shed_event(span, exc: "RequestRejected") -> None:
    """The shed VERDICT on the request's span — skipped when the span
    already carries a terminal event: a hot-swap drained past its
    bound delivers a 'reloading' RequestRejected to an ADMITTED
    request whose ``terminal(outcome=shed)`` the engine's
    ``fail_outstanding`` already stamped, and a second verdict would
    read as a double delivery to the exactly-one-terminal checker
    (chaos/invariants.py). Admission-gate sheds never reach the
    engine, so they always emit here."""
    if span is None:
        return
    if any(e.get("name") == "terminal" for e in span.events):
        return
    span.event("shed", reason=exc.reason,
               **({"tenant": exc.tenant}
                  if getattr(exc, "tenant", None) else {}))


def _shed_headers(exc: RequestRejected):
    """Response headers for one shed: Retry-After always; per-tenant
    sheds also carry ``X-Tenant-Shed`` so the router can tell a tenant
    verdict (surface it, keep the replica in rotation) from replica
    overload (back the replica off)."""
    hdrs = [("Retry-After", str(exc.retry_after_s))]
    if getattr(exc, "tenant", None):
        hdrs.append(("X-Tenant-Shed", str(exc.tenant)))
    return tuple(hdrs)


def _shed_body(exc: RequestRejected) -> dict:
    body = {"error": str(exc), "reason": exc.reason}
    if getattr(exc, "tenant", None):
        body["tenant"] = exc.tenant
    return body


def _admin_token_error(server: BundleServer, headers):
    """THE admin-endpoint token gate, shared by ``/admin/reload`` and
    ``/admin/profile`` so the 403/401 discipline cannot drift between
    them: no ``SERVE_ADMIN_TOKEN`` on the server → the endpoint does
    not exist operationally (403); configured → the caller must
    present it in ``X-Admin-Token``, compared constant-time
    (hmac.compare_digest — a byte-wise ``!=`` would leak the token
    prefix-by-prefix through response timing). Returns ``(status,
    body)`` to reply with, or ``None`` when authorized."""
    if not server.admin_token:
        return 403, {"error": "admin endpoint disabled (set "
                              "SERVE_ADMIN_TOKEN to enable)"}
    import hmac

    if not hmac.compare_digest(headers.get("X-Admin-Token") or "",
                               server.admin_token):
        return 401, {"error": "bad or missing X-Admin-Token"}
    return None


def _make_handler(server: BundleServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        _span = None  # the request's trace span (POST paths set it)

        def log_message(self, fmt, *args):  # route through our logger
            logger.info("%s %s", self.address_string(), fmt % args)

        def _reply(self, code: int, payload: dict, headers=()):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if self._span is not None:
                # EVERY response (successes and 429/503/504 sheds
                # alike) echoes the trace id — a user report quoting
                # X-Request-Id joins straight to GET /traces
                self.send_header("X-Request-Id", self._span.trace_id)
                self._span.set("http.status", code)
            for name, value in headers:
                self.send_header(name, value)
            if self.close_connection:
                # advertise the close (http.server's send_error does the
                # same) so pooling clients don't reuse a dying socket
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)

        def _stream_generate(self, req, prompts, tenant="default"):
            """Server-sent events: one ``data:`` line per token group,
            a terminal entry with the assembled completion, then
            ``data: [DONE]``. Greedy single-prompt only (that's the
            slot-engine path tokens stream FROM); the connection closes
            at the end — no Content-Length on a stream."""
            if len(prompts) != 1:
                server.record_metrics(failed=True)
                return self._reply(
                    400, {"error": "streaming takes exactly one prompt"})
            if (float(req.get("temperature", 0.0) or 0.0) > 0
                    or req.get("num_beams") or req.get("top_k")
                    or req.get("top_p") or req.get("repetition_penalty")):
                server.record_metrics(failed=True)
                return self._reply(
                    400, {"error": "streaming is greedy-only (no "
                                   "sampling/beam parameters)"})
            deadline_ms = req.get("deadline_ms")
            continuation = req.get("continuation")
            if continuation is not None:
                # the router's mid-stream failover splice: the ORIGINAL
                # prompt plus the token ids a dead replica already
                # delivered — ids must be sane non-negative ints (the
                # length budget is checked with the full prefill in
                # generate_stream)
                try:
                    emitted = [int(t)
                               for t in continuation["emitted_ids"]]
                    if not emitted or any(t < 0 for t in emitted):
                        raise ValueError
                    continuation = {"emitted_ids": emitted}
                except (TypeError, KeyError, ValueError):
                    server.record_metrics(failed=True)
                    return self._reply(
                        400, {"error": "'continuation' must carry "
                                       "emitted_ids: a non-empty list "
                                       "of non-negative token ids"})
            try:
                events = server.generate_stream(
                    prompts[0],
                    max_new_tokens=int(req.get("max_new_tokens", 64)),
                    deadline_s=(float(deadline_ms) / 1000.0
                                if deadline_ms is not None else None),
                    tenant=tenant, continuation=continuation,
                    span=self._span)
                first = next(events)  # validation errors surface BEFORE
                #   the 200 status line is committed
            except RequestRejected as exc:
                _span_shed_event(self._span, exc)
                server.record_metrics()
                return self._reply(exc.status, _shed_body(exc),
                                   headers=_shed_headers(exc))
            except (TypeError, ValueError) as exc:
                server.record_metrics(failed=True)
                return self._reply(400, {"error": str(exc)})
            self.close_connection = True
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            if self._span is not None:
                self.send_header("X-Request-Id", self._span.trace_id)
                self._span.set("http.status", 200)
            self.end_headers()
            try:
                if self._span is not None:
                    # first SSE line: a comment carrying the trace id,
                    # so stream consumers (which never see response
                    # headers through some SSE clients) can still join
                    # the stream to /traces
                    self.wfile.write(
                        f": trace_id={self._span.trace_id}\n\n".encode())
            except OSError:
                pass
            try:
                for event in itertools.chain([first], events):
                    self.wfile.write(
                        f"data: {json.dumps(event)}\n\n".encode())
                    self.wfile.flush()
                self.wfile.write(b"data: [DONE]\n\n")
            except Exception as exc:  # noqa: BLE001 — mid-stream: the
                # status line is gone; emit an error event if the socket
                # still listens, else just drop (client sees the cut)
                logger.exception("stream failed mid-flight")
                try:
                    self.wfile.write(
                        f"data: {json.dumps({'error': str(exc)})}"
                        "\n\n".encode())
                    self.wfile.write(b"data: [DONE]\n\n")
                except OSError:
                    pass

        def do_GET(self):
            route = self.path.partition("?")[0]  # scrape configs may
            # append query params; routing must ignore them
            if route in ("/healthz", "/health", "/"):
                # draining → 503: the k8s readiness probe fails and the
                # Service stops routing here, while /metrics and /events
                # below keep answering (drain is exactly when you want
                # to watch the queue empty)
                return self._reply(503 if server.draining else 200,
                                   server.health())
            if route == "/livez":
                # LIVENESS, distinct from readiness: no engine lock,
                # no load math — 503 only when the driver loop itself
                # has stalled past live_stall_s (the k8s livenessProbe
                # target; draining answers 200 live)
                out = server.livez()
                return self._reply(200 if out["live"] else 503, out)
            if route == "/loadz":
                # the router's prober polls this every second per
                # replica: one dict assembly, no registry walk, no
                # Prometheus text parse on the other end. Draining
                # answers 200 — the field carries the state; the 503
                # convention stays on /healthz (readiness)
                return self._reply(200, server.loadz())
            # /metrics, /metrics.json, /events — the obs package owns
            # the response assembly; this server contributes the live
            # engine-gauge refresh and its legacy alias block
            extra = ""
            if route == "/metrics":
                server._refresh_engine_gauges()
                extra = server._legacy_metrics_text()
            front = getattr(server, "_front", None)
            out = handle_obs_request(self.path, server.registry,
                                     server.event_log,
                                     extra_exposition=extra,
                                     tracer=getattr(server, "tracer",
                                                    None),
                                     stepstats=(front.stepstats
                                                if front is not None
                                                else None))
            if out is None:
                return self._reply(404,
                                   {"error": f"unknown path {self.path}"})
            code, ctype, body = out
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            server._http_enter()  # drain() waits for this to reach zero
            tracer = getattr(server, "tracer", None)
            if tracer is not None:
                # adopt the caller's traceparent (the router's, or an
                # end client's) or mint a new root; malformed input
                # degrades to a fresh trace, never an error
                self._span = tracer.start_span(
                    "serve.request",
                    parent=self.headers.get("traceparent"),
                    attrs={"path": self.path.partition("?")[0]})
            try:
                with use_span(self._span):
                    self._do_POST()
            finally:
                if self._span is not None:
                    self._span.finish()
                # handler instances live per keep-alive CONNECTION, not
                # per request: a later GET on the same socket must not
                # echo (or stamp onto) this finished span
                self._span = None
                server._http_exit()

        def _do_POST(self):
            if server.draining:
                # shed BEFORE reading the body — the connection is
                # closing anyway, so the keep-alive desync the 413 path
                # guards against doesn't apply
                self.close_connection = True
                server.record_metrics()
                server._obs["serve_requests_rejected_total"].labels(
                    reason="draining").inc()
                exc = _draining_rejection()
                if self._span is not None:
                    self._span.event("shed", reason=exc.reason)
                return self._reply(
                    exc.status, {"error": str(exc), "reason": exc.reason},
                    headers=(("Retry-After", str(exc.retry_after_s)),))
            try:
                n = int(self.headers.get("Content-Length", 0))
                if n > MAX_BODY_BYTES:
                    # Replying without reading the body desyncs an
                    # HTTP/1.1 keep-alive stream (the unread bytes would
                    # parse as the next request) — drop the connection.
                    self.close_connection = True
                    server.record_metrics(failed=True)
                    return self._reply(413, {
                        "error": f"body too large ({n} bytes > "
                                 f"{MAX_BODY_BYTES})"})
                req = json.loads(self.rfile.read(n) or b"{}")
            except (ValueError, json.JSONDecodeError) as exc:
                server.record_metrics(failed=True)
                return self._reply(400, {"error": f"bad JSON body: {exc}"})
            try:
                # chaos: the BundleServer request-front fault point — a
                # fail rule lands in the generic handler below as an
                # explicit 500 error terminal (counted, never a hang);
                # a slow rule injects scheduled front latency
                chaos_fire("serve.request")
                deadline_ms = req.get("deadline_ms") if isinstance(
                    req, dict) else None
                deadline_s = (float(deadline_ms) / 1000.0
                              if deadline_ms is not None else None)
                # tenant identity: X-Tenant header wins, then the body
                # field, then "default" — one extraction point shared
                # by the blocking and streaming generate paths (the
                # router forwards the same header)
                tenant = self.headers.get("X-Tenant") or (
                    req.get("tenant") if isinstance(req, dict)
                    else None) or "default"
                if not isinstance(tenant, str):
                    server.record_metrics(failed=True)
                    return self._reply(
                        400, {"error": "'tenant' must be a string"})
                if self.path == "/v1/generate":
                    prompts = req.get("prompts")
                    if prompts is None and "prompt" in req:
                        prompts = [req["prompt"]]
                    if not isinstance(prompts, list) or not all(
                            isinstance(p, str) for p in prompts or [None]):
                        server.record_metrics(failed=True)
                        return self._reply(
                            400, {"error": "'prompts' must be a list of "
                                           "strings (or 'prompt': str)"})
                    seed = req.get("seed")
                    if seed is not None:
                        try:
                            seed = int(seed)
                        except (TypeError, ValueError):
                            server.record_metrics(failed=True)
                            return self._reply(
                                400, {"error": "'seed' must be an "
                                               "integer"})
                    if req.get("stream"):
                        return self._stream_generate(req, prompts,
                                                     tenant=tenant)
                    out = server.generate(
                        prompts,
                        max_new_tokens=int(req.get("max_new_tokens", 64)),
                        temperature=float(req.get("temperature", 0.0)),
                        top_k=req.get("top_k"),
                        top_p=req.get("top_p"),
                        num_beams=int(req.get("num_beams", 0)),
                        repetition_penalty=req.get("repetition_penalty"),
                        deadline_s=deadline_s, tenant=tenant,
                        seed=seed, span=self._span)
                    server.record_metrics(
                        generate_entries=out,
                        trace_id=(self._span.trace_id
                                  if self._span is not None else None))
                    self._reply(200, {"completions": out})
                elif self.path == "/v1/warm":
                    prefix = req.get("prefix")
                    if not isinstance(prefix, str):
                        server.record_metrics(failed=True)
                        return self._reply(
                            400, {"error": "'prefix' must be a string"})
                    out = server.warm_prefix(prefix)
                    server.record_metrics()
                    self._reply(200, out)
                elif self.path == "/admin/reload":
                    # bundle hot-swap (the coordinator's publish path).
                    # Token gate shared with /admin/profile
                    # (_admin_token_error): 403 unconfigured, 401
                    # mismatch. The reload itself serializes (409
                    # while one is in flight) and rolls back on failure.
                    err = _admin_token_error(server, self.headers)
                    if err is not None:
                        server.record_metrics()
                        server._obs["serve_bundle_reloads_total"].labels(
                            outcome="rejected").inc()
                        return self._reply(err[0], err[1])
                    bundle = req.get("bundle")
                    if not isinstance(bundle, str) or not bundle:
                        server.record_metrics(failed=True)
                        return self._reply(
                            400, {"error": "'bundle' must be a bundle "
                                           "directory path"})
                    generation = req.get("generation")
                    out = server.reload_bundle(
                        _resolve_bundle(bundle),
                        generation=generation,
                        canary=bool(req.get("canary", True)))
                    server.record_metrics()
                    self._reply(200, out)
                elif self.path == "/admin/profile":
                    # on-demand xprof capture over the next N busy
                    # engine steps — same token gate (403/401) and
                    # one-at-a-time 409 discipline as /admin/reload;
                    # 202: the capture is ARMED, completion lands on
                    # /events as profile_trace_written
                    err = _admin_token_error(server, self.headers)
                    if err is not None:
                        server.record_metrics()
                        return self._reply(err[0], err[1])
                    out = server.start_profile(
                        req.get("output_dir"),
                        steps=int(req.get("steps", 8)))
                    server.record_metrics()
                    self._reply(202, out)
                elif self.path == "/v1/prefill":
                    # disaggregated handoff, prefill side: warm +
                    # export the prompt's KV pages as one page blob
                    prompt = req.get("prompt")
                    if not isinstance(prompt, str):
                        server.record_metrics(failed=True)
                        return self._reply(
                            400, {"error": "'prompt' must be a string"})
                    out = server.prefill_export(prompt)
                    server.record_metrics()
                    self._reply(200, out)
                elif self.path == "/v1/kv_import":
                    # disaggregated handoff, decode side: install a
                    # transferred page blob + adopt it into the trie
                    blob = req.get("blob")
                    if not isinstance(blob, str):
                        server.record_metrics(failed=True)
                        return self._reply(
                            400, {"error": "'blob' must be a base64 "
                                           "string"})
                    out = server.kv_import(blob)
                    server.record_metrics()
                    self._reply(200, out)
                elif self.path == "/v1/score":
                    texts = req.get("texts")
                    if not isinstance(texts, list) or not all(
                            isinstance(t, str) for t in texts or [None]):
                        server.record_metrics(failed=True)
                        return self._reply(
                            400, {"error": "'texts' must be a list of "
                                           "strings"})
                    scores = server.score(texts, tenant=tenant)
                    server.record_metrics(score=True)
                    self._reply(200, {"scores": scores})
                else:
                    server.record_metrics(failed=True)
                    self._reply(404, {"error": f"unknown path {self.path}"})
            except RequestRejected as exc:
                # load shedding is not a server fault: counted in the
                # rejected{reason} family (incremented at the raise
                # site), not in requests_failed. Per-tenant sheds carry
                # the tenant in body + X-Tenant-Shed header; the shed
                # VERDICT lands on the trace (reason + whose quota) —
                # unless the engine already stamped the terminal
                server.record_metrics()
                _span_shed_event(self._span, exc)
                self._reply(exc.status, _shed_body(exc),
                            headers=_shed_headers(exc))
            except DeadlineExceeded as exc:
                # the dedicated deadline counter (incremented where the
                # expiry was detected) carries the signal
                server.record_metrics()
                self._reply(504, {"error": str(exc)})
            except ReloadInFlight as exc:
                server.record_metrics()
                server._obs["serve_bundle_reloads_total"].labels(
                    outcome="rejected").inc()
                self._reply(409, {"error": str(exc)})
            except ProfileInFlight as exc:
                server.record_metrics()
                self._reply(409, {"error": str(exc)})
            except BundleReloadError as exc:
                # the old generation is serving either way; the body
                # says whether a swap happened and was rolled back
                server.record_metrics(failed=True)
                self._reply(502, {
                    "error": str(exc),
                    "rolled_back": exc.rolled_back,
                    "bundle_generation": server.bundle_generation})
            except (TypeError, ValueError) as exc:
                # TypeError too: int(None)/float([]) from JSON null/list
                # field values is caller error, not a server fault
                server.record_metrics(failed=True)
                self._reply(400, {"error": str(exc)})
            except Exception as exc:  # noqa: BLE001 — keep the server up
                logger.exception("request failed")
                server.record_metrics(failed=True)
                self._reply(500, {"error": f"{type(exc).__name__}: {exc}"})

    return Handler


def start_http_server(server: BundleServer, host: str = "0.0.0.0",
                      port: int = 8000) -> ThreadingHTTPServer:
    """Bind and return the HTTP server (``port=0`` → ephemeral; read the
    bound port from ``.server_address[1]``). Caller runs
    ``serve_forever`` (the CLI) or a daemon thread (tests)."""
    httpd = ThreadingHTTPServer((host, port), _make_handler(server))
    return httpd


# -- CLI ---------------------------------------------------------------------


def parse_args(argv=None) -> argparse.Namespace:
    e = os.environ.get
    p = argparse.ArgumentParser(
        description="Serve an exported bundle over HTTP (or stdin)")
    p.add_argument("--bundle", default=e("BUNDLE_DIR"), required=e("BUNDLE_DIR") is None,
                   help="directory written by train/export.py (local or gs://)")
    p.add_argument("--host", default=e("SERVE_HOST", "0.0.0.0"))
    p.add_argument("--port", type=int, default=int(e("SERVE_PORT", "8000")))
    p.add_argument("--tp", type=int, default=int(e("SERVE_TP", "0")),
                   help="tensor-parallel ways (0/1 = single device)")
    p.add_argument("--int8-kv", action="store_true",
                   default=e("SERVE_INT8_KV", "") == "1",
                   help="serve with an int8 KV cache even if the bundle "
                        "wasn't exported with one")
    p.add_argument("--draft-bundle", default=e("DRAFT_BUNDLE_DIR", ""),
                   help="a smaller bundle (same tokenizer/vocab) used as "
                        "the speculative-decoding draft for single-prompt "
                        "greedy requests — identical tokens, lower latency")
    p.add_argument("--continuous-slots", type=int,
                   default=int(e("CONTINUOUS_SLOTS", "0")),
                   help="enable continuous batching with this many KV "
                        "slots (0 = whole-batch serving). Greedy "
                        "requests from ALL connections share the slot "
                        "pool; composes with --tp and multi-host "
                        "(device ops replayed over the announce wire)")
    p.add_argument("--prefix-cache", type=int,
                   default=int(e("PREFIX_CACHE", "0")),
                   help="prefix caching (0 = off; requires "
                        "--continuous-slots). PAGED bundles get the "
                        "engine-level radix cache over the KV page "
                        "pool — completed prompts stay resident as "
                        "refcounted pages, same-prefix admissions "
                        "share them copy-on-write and prefill only "
                        "the suffix; the value caps the cache's "
                        "RESIDENT pages (use the pool size for "
                        "whole-pool caching; composes with "
                        "multi-host). Dense bundles keep the batch-1 "
                        "LRU with this many entries (POST /v1/warm; "
                        "single-host)")
    p.add_argument("--prefill-chunk", "--prefill-chunk-tokens",
                   dest="prefill_chunk", type=int,
                   default=int(e("PREFILL_CHUNK", "0")),
                   help="chunked prefill: admit prompts longer than "
                        "this in bounded pieces with decode chunks "
                        "interleaved (0 = whole-prompt prefill; "
                        "requires --continuous-slots; paged engines "
                        "write pieces straight into the page pool and "
                        "replay chunk progress over the multi-host "
                        "wire; dense engines are single-host)")
    p.add_argument("--step-token-budget", type=int,
                   default=int(e("STEP_TOKEN_BUDGET", "0")),
                   help="cap the tokens one engine step dispatches, "
                        "split between one prefill piece and the "
                        "decode chunk (live_slots x steps) — bounds "
                        "time-between-tokens under long-prompt "
                        "arrivals (0 = off; pair with "
                        "--prefill-chunk)")
    p.add_argument("--continuous-chunk", type=int,
                   default=int(e("CONTINUOUS_CHUNK", "8")),
                   help="decode steps per engine dispatch between "
                        "admission points")
    p.add_argument("--spec-tokens", type=int,
                   default=int(e("SERVE_SPEC_TOKENS", "0")),
                   help="in-engine speculative decoding: draft k "
                        "tokens per slot per round, verify all k+1 in "
                        "ONE multi-query forward — greedy token-exact, "
                        ">1 token per verify when the draft agrees "
                        "(0 = off; requires --continuous-slots; uses "
                        "--draft-bundle as the draft, else the target "
                        "SELF-drafts, which is correctness-only; "
                        "draft+verify tokens count against "
                        "--step-token-budget; accept rate on /loadz "
                        "spec_accept_rate)")
    def _pipeline_depth(v: str) -> int:
        n = int(v)
        if not 0 <= n <= 4:
            # fail fast at argparse time, not after the bundle loads;
            # depth beyond a few chunks only adds token latency and
            # discarded post-eos decode work
            raise argparse.ArgumentTypeError(
                f"--continuous-pipeline must be 0..4, got {n}")
        return n

    p.add_argument("--continuous-pipeline", type=_pipeline_depth,
                   default=int(e("CONTINUOUS_PIPELINE", "1")),
                   help="decode-ahead depth: keep up to N dispatched "
                        "chunks un-collected so step N's host work "
                        "(scheduling, collect bookkeeping, delivery) "
                        "overlaps the in-flight chunk's compute "
                        "(default 1 — the async engine core; 0 = the "
                        "serial A/B reference loop; measured +52%% "
                        "engine tokens/sec over a remote-attached chip "
                        "at chunk 64 depth 1; depth >=2 is single-host "
                        "only — the engine enforces it; multi-host: "
                        "the chunk is announced dispatch-only and the "
                        "gathers replay at OP_CB_COLLECT)")
    p.add_argument("--schedule", choices=("fifo", "longest"),
                   default=e("CB_SCHEDULE", "fifo"),
                   help="slot admission policy: fifo (arrival order) or "
                        "longest (LPT: longest remaining budget first — "
                        "smaller makespan / higher chip utilization, at "
                        "the cost of short-request queueing latency)")
    p.add_argument("--adaptive-chunk", action="store_true",
                   default=e("ADAPTIVE_CHUNK", "") not in ("", "0"),
                   help="budget-aligned chunking: size each engine "
                        "dispatch to the minimum remaining token budget "
                        "over the active slots (bucketed powers of two "
                        "down to 8), so a slot whose request ends at its "
                        "budget frees at the earliest collect instead of "
                        "decoding dead rows to the end of a fixed chunk")
    p.add_argument("--max-queue-depth", type=int,
                   default=int(e("MAX_QUEUE_DEPTH", "0")),
                   help="bounded admission: shed (HTTP 429 + "
                        "Retry-After) once this many requests wait for "
                        "a KV slot (0 = unbounded); overload degrades "
                        "to fast rejection instead of collapse")
    p.add_argument("--max-queued-tokens", type=int,
                   default=int(e("MAX_QUEUED_TOKENS", "0")),
                   help="bounded admission by token budget: shed when "
                        "queued prompt+budget tokens would exceed this "
                        "(0 = unbounded)")
    p.add_argument("--tenants", default=e("SERVE_TENANTS", ""),
                   help="multi-tenant fairness/quota spec: JSON "
                        "('{\"light\": {\"weight\": 3}, \"noisy\": "
                        "{\"weight\": 1, \"rate\": 200, \"burst\": "
                        "400}}') or compact "
                        "name=weight[:rate[:burst]],... — weights "
                        "drive DWRR admission shares and each "
                        "tenant's slice of --max-queue-depth/"
                        "--max-queued-tokens; rate (tokens/sec) + "
                        "burst build per-tenant token buckets "
                        "(429 + Retry-After from the tenant's own "
                        "refill; other tenants keep admitting). A "
                        "'*' entry configures unlisted tenants. "
                        "Empty = tenancy off (global bounds)")
    p.add_argument("--trace-sample", type=float,
                   default=float(e("TRACE_SAMPLE", "0.01")),
                   help="fraction of requests whose traces are "
                        "RETAINED in the /traces flight recorder "
                        "(0..1). Ids always propagate (traceparent "
                        "in, X-Request-Id out) regardless; 0 with "
                        "--trace-slow-ms 0 disables recording "
                        "entirely (id propagation only)")
    p.add_argument("--trace-slow-ms", type=float,
                   default=float(e("TRACE_SLOW_MS", "1000")),
                   help="always-on slow capture: any request slower "
                        "than this is retained in /traces even when "
                        "the sampler skipped it — tail latency is "
                        "never lost to sampling (0 = off)")
    p.add_argument("--drain-timeout", type=float,
                   default=float(e("DRAIN_TIMEOUT", "30")),
                   help="seconds SIGTERM waits for in-flight requests "
                        "before exiting; pair with a k8s "
                        "terminationGracePeriodSeconds comfortably "
                        "above it (see infra/k8s/tpu/tpu-serve.yaml)")
    p.add_argument("--chaos", default=e("SERVE_CHAOS", ""),
                   help="serve-side fault injection: legacy driver-"
                        "loop tokens (fail@STEP / slow@STEP:SECONDS, "
                        "e.g. 'fail@50,slow@80:0.5' — the engine-"
                        "rebuild path) and/or NAMED fault points "
                        "(POINT:ACTION@N / POINT:ACTION%%P, e.g. "
                        "'engine.device_step:hang@3:2,"
                        "serve.request:fail%%0.05,seed=7' — see "
                        "docs/CHAOS.md for the point catalog); "
                        "NEVER set in production")
    p.add_argument("--step-record-ring", type=int,
                   default=int(e("SERVE_STEP_RECORD_RING", "256")),
                   help="step telemetry: keep the last N engine-step "
                        "records (per-phase timing + batch "
                        "composition) in the GET /stepz ring; the "
                        "windowed host-overhead fraction rides /loadz "
                        "as step_host_overhead_frac (continuous-slots "
                        "mode only)")
    p.add_argument("--role", choices=("mixed", "prefill", "decode"),
                   default=e("SERVE_ROLE", "mixed"),
                   help="disaggregated serving role, advertised on "
                        "/loadz: the router sends long-prompt "
                        "admissions to 'prefill' replicas (chunked "
                        "prefill + KV-page export) and generate "
                        "traffic to 'decode'/'mixed' ones. Advisory — "
                        "every role serves every endpoint, so a "
                        "degraded fleet falls back cleanly")
    p.add_argument("--peak-flops", type=float,
                   default=float(e("SERVE_PEAK_FLOPS", "0")),
                   help="per-chip peak FLOPs/sec for the serve_mfu "
                        "gauge (e.g. 1.97e14 for v5e bf16); 0 = MFU "
                        "disabled — the CPU default, where a peak "
                        "number would be meaningless")
    p.add_argument("--step-timeout", type=float,
                   default=float(e("SERVE_STEP_TIMEOUT", "0")),
                   help="step watchdog: when one engine step (device "
                        "dispatch) runs longer than this many "
                        "seconds, every in-flight request is failed "
                        "with an explicit error terminal and the "
                        "engine rebuilds when the step returns — a "
                        "hung device step costs bounded client "
                        "latency instead of a wedged loop (0 = off; "
                        "size WELL above worst-case compile + chunk "
                        "time)")
    p.add_argument("--live-stall", type=float,
                   default=float(e("SERVE_LIVE_STALL", "120")),
                   help="GET /livez answers 503 once the engine "
                        "driver loop has not completed an iteration "
                        "for this many seconds (the k8s livenessProbe "
                        "target; 0 disables the stall check)")
    p.add_argument("--heartbeat-file", default=e("HEARTBEAT_FILE", ""),
                   help="node-local path the engine DRIVER LOOP beats "
                        "(train/resilience.Heartbeat); the k8s liveness "
                        "probe watches its age, catching a wedged "
                        "device loop that /healthz (answered from an "
                        "HTTP thread) cannot see. Continuous-slots "
                        "mode only")
    p.add_argument("--metrics-textfile", default=e("METRICS_TEXTFILE", ""),
                   help="also export the metrics registry to this .prom "
                        "file every --metrics-interval seconds (atomic "
                        "rename; point node-exporter's textfile collector "
                        "at the directory — scraping without a Service)")
    p.add_argument("--metrics-interval", type=float,
                   default=float(e("METRICS_INTERVAL", "15")))
    p.add_argument("--stdin", action="store_true",
                   help="serve stdin lines instead of HTTP: each input "
                        "line is a prompt, each output line a JSON result")
    p.add_argument("--max-new-tokens", type=int,
                   default=int(e("MAX_NEW_TOKENS", "64")))
    p.add_argument("--temperature", type=float,
                   default=float(e("TEMPERATURE", "0.0")))
    # multi-host: same bootstrap flags as the trainers. Process 0 runs
    # the HTTP server; the rest replay announced requests
    # (train/serving.py serve_worker_loop). Greedy decode only.
    p.add_argument("--num-processes", type=int,
                   default=int(e("NUM_PROCESSES", "1")))
    p.add_argument("--process-id", type=int,
                   default=int(e("PROCESS_ID", "-1")))
    p.add_argument("--coordinator-addr", default=e("COORDINATOR_ADDR", ""))
    p.add_argument("--coordinator-port", type=int,
                   default=int(e("COORDINATOR_PORT", "8476")))
    return p.parse_args(argv)


def _resolve_bundle(path: str) -> str:
    """gs:// bundles are pulled to a local spool first (orbax restores
    from a directory tree; the CSV/TFRecord loaders stream, but a
    one-time bundle pull is the right trade for serving)."""
    if "://" not in path:
        return path
    import tempfile

    from pyspark_tf_gke_tpu.utils.fs import fs_copy_tree

    local = tempfile.mkdtemp(prefix="bundle-")
    logger.info("pulling %s -> %s", path, local)
    fs_copy_tree(path, local)
    return local


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.num_processes > 1:
        from pyspark_tf_gke_tpu.parallel.distributed import (
            initialize_distributed,
        )

        initialize_distributed(
            num_processes=args.num_processes,
            process_id=args.process_id,
            coordinator_addr=args.coordinator_addr,
            coordinator_port=args.coordinator_port)
    mesh = None
    if jax.process_count() > 1:
        # one mesh over ALL global devices: tp as asked, dp on the rest
        # (the -1 wildcard gives a clear divisibility error for bad --tp)
        from pyspark_tf_gke_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"dp": -1, "tp": max(args.tp, 1)}, jax.devices())
    elif args.tp and args.tp > 1:
        from pyspark_tf_gke_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"tp": args.tp}, jax.devices()[:args.tp])
    server = BundleServer(
        _resolve_bundle(args.bundle), mesh=mesh, int8_kv=args.int8_kv,
        draft_bundle_dir=(_resolve_bundle(args.draft_bundle)
                          if args.draft_bundle else ""),
        continuous_slots=args.continuous_slots,
        continuous_chunk=args.continuous_chunk,
        prefix_cache_size=args.prefix_cache,
        prefill_chunk=args.prefill_chunk,
        step_token_budget=args.step_token_budget,
        continuous_pipeline=args.continuous_pipeline,
        adaptive_chunk=args.adaptive_chunk,
        schedule=args.schedule,
        max_queue_depth=args.max_queue_depth,
        max_queued_tokens=args.max_queued_tokens,
        chaos_spec=args.chaos,
        heartbeat_file=args.heartbeat_file,
        tenants_spec=args.tenants,
        trace_sample=args.trace_sample,
        trace_slow_ms=args.trace_slow_ms,
        step_timeout_s=args.step_timeout,
        live_stall_s=args.live_stall,
        spec_tokens=args.spec_tokens,
        step_record_ring=args.step_record_ring,
        peak_flops=args.peak_flops,
        role=args.role,
        # env-only by design: a token flag would leak into ps output
        # and pod specs; the k8s manifest mounts it from a Secret
        admin_token=os.environ.get("SERVE_ADMIN_TOKEN", ""))
    if args.chaos:
        logger.warning("serve-side chaos injection ACTIVE: %s", args.chaos)
    logger.info("bundle loaded: %s", server.health())
    exporter = None
    if args.metrics_textfile:
        from pyspark_tf_gke_tpu.obs.export import TextfileExporter

        exporter = TextfileExporter(server.registry, args.metrics_textfile,
                                    args.metrics_interval).start()
    if jax.process_count() > 1:
        # fail a misdeploy (draft bundle on some processes only) at
        # startup, not mid-collective on the first speculative request
        from pyspark_tf_gke_tpu.train.serving import sync_serving_config

        sync_serving_config(server.draft_model is not None)

    if jax.process_count() > 1 and jax.process_index() != 0:
        # workers: no HTTP socket — replay every announced request until
        # process 0 shuts the job down
        from pyspark_tf_gke_tpu.train.serving import serve_worker_loop

        if threading.current_thread() is threading.main_thread():
            import signal

            # a rolling restart SIGTERMs EVERY pod: a worker dying
            # immediately would sever the announce wire while pod 0 is
            # still draining, failing the very in-flight requests the
            # grace window protects. Ignore it — the loop ends when
            # process 0 announces shutdown (end of its drain), and the
            # k8s SIGKILL at the end of the grace period is the
            # backstop for a wedged drain.
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
        served = serve_worker_loop(server.model, server.params, server.mesh,
                                   draft_model=server.draft_model,
                                   draft_params=server.draft_params)
        logger.info("worker loop done after %d requests", served)
        return 0

    try:
        # ONE finally covers everything process 0 does from here: a
        # failure anywhere (port already bound, broken stdin pipe, ...)
        # must still release the worker loops, or a local error becomes
        # a pod-wide jax.distributed fatal cascade.
        if args.stdin:
            for line in sys.stdin:
                prompt = line.rstrip("\n")
                if not prompt:
                    continue
                try:
                    out = server.generate(
                        [prompt], max_new_tokens=args.max_new_tokens,
                        temperature=args.temperature)[0]
                except ValueError as exc:
                    # a bad line (over-long, zero tokens) must not take
                    # the loaded model down with it — mirror the HTTP
                    # 400 path
                    out = {"prompt": prompt, "error": str(exc)}
                print(json.dumps(out), flush=True)
            return 0

        httpd = start_http_server(server, args.host, args.port)
        logger.info(
            "serving on http://%s:%d (healthz, /v1/generate, /v1/score)",
            *httpd.server_address[:2])

        def _drain_then_stop():
            # graceful drain (the k8s rolling-restart contract):
            # readiness flips to draining → admission stops → in-flight
            # requests finish (bounded by --drain-timeout) → the accept
            # loop stops → main() falls through its finally and exits 0
            server.begin_drain()
            drained = server.drain(args.drain_timeout)
            logger.info("drain %s after SIGTERM; stopping HTTP server",
                        "complete" if drained else
                        f"TIMED OUT at {args.drain_timeout}s")
            httpd.shutdown()

        if threading.current_thread() is threading.main_thread():
            import signal

            signal.signal(
                signal.SIGTERM,
                lambda signum, frame: threading.Thread(
                    target=_drain_then_stop, name="drain",
                    daemon=True).start())
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            logger.info("shutting down")
            httpd.shutdown()
        return 0
    finally:
        if exporter is not None:
            exporter.stop()  # final write captures the shutdown state
        if server._front is not None:
            server._front.shutdown()
        if jax.process_count() > 1:
            from pyspark_tf_gke_tpu.train.serving import announce_shutdown

            announce_shutdown()  # release the worker loops


if __name__ == "__main__":
    sys.exit(main())
